"""Normal-task transport: leasing, pipelining, spillback handling.

Parity: reference ``src/ray/core_worker/transport/direct_task_transport.cc``
— per-``SchedulingKey`` queues (direct_task_transport.h:53-57), worker lease
reuse (``OnWorkerIdle`` .cc:157), new lease requests capped per scheduling
class (``RequestNewWorkerIfNeeded`` .cc:308), spillback re-lease at
``retry_at_raylet_address`` (.cc:459), direct ``PushTask`` to the leased
worker (.cc:508) — the raylet is off the per-task data path after leasing.

Lease-node choice uses the locality policy (``lease_policy.h:54-60``): the
raylet holding the most argument bytes, else the local raylet.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple

from ray_tpu import exceptions
from ray_tpu._private.config import get_config
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu._private.debug import diag_rlock

# Re-lease cadence/window for leases bounced off a not-yet-declared-dead
# node: 0.2s x 150 = 30s, comfortably past any heartbeat-timeout
# declaration, after which the bounce becomes a real failure.
_LEASE_BOUNCE_DELAY_S = 0.2
_MAX_LEASE_BOUNCES = 150


class _SchedulingKeyState:
    __slots__ = ("queue", "idle_workers", "pending_leases", "leased_task_ids")

    def __init__(self):
        self.queue: deque = deque()
        self.idle_workers: List[Tuple[object, object]] = []  # (worker, raylet)
        self.pending_leases = 0
        # Task ids with an in-flight lease request: each lease request must
        # carry a DISTINCT representative spec — the raylet dep-waits on the
        # representative's args, and two in-flight waits for one task id
        # would collide (reference: pending_lease_requests_ keyed by TaskID,
        # direct_task_transport.h).
        self.leased_task_ids: set = set()


class DirectTaskSubmitter:
    def __init__(self, core_worker):
        self._core = core_worker
        self._lock = diag_rlock("DirectTaskSubmitter._lock")
        self._keys: Dict[int, _SchedulingKeyState] = defaultdict(
            _SchedulingKeyState)
        self._lease_bounces: Dict = {}   # task_id -> transient rejects
        self._max_pending = get_config(
        ).max_pending_lease_requests_per_scheduling_category

    # ---- entry ----------------------------------------------------------
    def submit(self, spec: TaskSpec):
        key = spec.scheduling_class
        with self._lock:
            state = self._keys[key]
            state.queue.append(spec)
        self._pump(key)

    def _pump(self, key: int):
        """Dispatch queued tasks onto idle leased workers; request new
        leases for the remainder (bounded pipelining)."""
        while True:
            with self._lock:
                state = self._keys[key]
                if not state.queue:
                    return
                if state.idle_workers:
                    worker, raylet = state.idle_workers.pop()
                    spec = state.queue.popleft()
                    self._push(spec, worker, raylet, key)
                    continue
                if state.pending_leases >= self._max_pending:
                    return
                spec = next((s for s in state.queue
                             if s.task_id not in state.leased_task_ids), None)
                if spec is None:
                    return  # every queued task already has a lease in flight
                state.pending_leases += 1
                state.leased_task_ids.add(spec.task_id)
            self._request_lease(spec, key)
            return

    # ---- leasing --------------------------------------------------------
    def _pick_lease_raylet(self, spec: TaskSpec):
        """Locality-aware lease policy (lease_policy.h:54-60)."""
        best, best_bytes = None, -1
        cluster = self._core.cluster
        for oid in spec.arg_object_ids():
            locs = cluster.object_directory.get_locations(oid)
            for node_id in locs:
                raylet = cluster.gcs.raylet(node_id)
                if raylet is None:
                    continue
                entry = raylet.object_store.get(oid)
                size = entry.size if entry else 0
                if size > best_bytes:
                    best, best_bytes = raylet, size
        if spec.scheduling_options.node_affinity_node_id is not None:
            affinity = cluster.gcs.raylet(
                spec.scheduling_options.node_affinity_node_id)
            if affinity is not None:
                return affinity
        return best or self._core.local_raylet

    def _request_lease(self, spec: TaskSpec, key: int, raylet=None,
                       hops: int = 0):
        raylet = raylet or self._pick_lease_raylet(spec)
        if raylet is None:
            self._on_lease_failed(spec, key,
                                  exceptions.RayTpuError("no raylet"))
            return

        def on_reply(result):
            if "worker" in result:
                with self._lock:
                    state = self._keys[key]
                    state.pending_leases -= 1
                    state.leased_task_ids.discard(spec.task_id)
                    self._lease_bounces.pop(spec.task_id, None)
                    if state.queue and state.queue[0].task_id == spec.task_id:
                        state.queue.popleft()
                        dispatch = spec
                    elif state.queue:
                        dispatch = state.queue.popleft()
                    else:
                        dispatch = None
                    if dispatch is not None:
                        state.leased_task_ids.discard(dispatch.task_id)
                if dispatch is None:
                    # Queue drained while the lease was in flight; return it.
                    result["raylet"].return_worker(result["worker"])
                else:
                    self._push(dispatch, result["worker"], result["raylet"],
                               key)
                self._pump(key)
            elif "retry_at" in result:
                # Spillback (cluster_task_manager.cc:285-323): re-lease at
                # the suggested raylet.
                target = self._core.cluster.gcs.raylet(result["retry_at"])
                if target is None or hops > 10:
                    with self._lock:
                        self._keys[key].pending_leases -= 1
                        self._keys[key].leased_task_ids.discard(spec.task_id)
                    self._pump(key)
                else:
                    self._request_lease(spec, key, raylet=target,
                                        hops=hops + 1)
            else:
                reason = str(result.get("reason", "lease rejected"))
                transient = bool(result.get("rejected")) and (
                    "connection lost" in reason or "node dead" in reason)
                self._on_lease_failed(
                    spec, key, exceptions.RayTpuError(reason),
                    transient=transient)

        raylet.request_worker_lease(spec, on_reply)

    def _on_lease_failed(self, spec: TaskSpec, key: int, err,
                         transient: bool = False):
        with self._lock:
            state = self._keys[key]
            state.pending_leases = max(0, state.pending_leases - 1)
            state.leased_task_ids.discard(spec.task_id)
            try:
                state.queue.remove(spec)
            except ValueError:
                pass
        if transient:
            # The lease bounced off a dying/unreachable node whose death
            # the GCS has not declared yet, so the scheduler may keep
            # pointing at it for a few heartbeats.  That is a
            # scheduling-plane hiccup, not a task failure: hold the spec
            # and re-lease after a beat WITHOUT burning the task's retry
            # budget (reference: lease failures against a dead raylet are
            # retried at the lease layer, task retries cover execution).
            # Bounded — past the window it becomes a real failure.
            with self._lock:
                n = self._lease_bounces.get(spec.task_id, 0) + 1
                self._lease_bounces[spec.task_id] = n
            if n <= _MAX_LEASE_BOUNCES:
                # Delayed re-lease rides the raylet event loop's timer
                # heap — a node death can bounce hundreds of queued
                # tasks every 0.2s for several heartbeats, and a Timer
                # THREAD per bounce would be thread churn exactly while
                # the scheduler is busiest.
                raylet = self._core.local_raylet
                if raylet is not None and not getattr(raylet, "_dead",
                                                      False):
                    raylet.loop.schedule_after(
                        _LEASE_BOUNCE_DELAY_S,
                        lambda: self._resubmit_bounced(spec),
                        "lease.rebounce")
                return
        with self._lock:
            self._lease_bounces.pop(spec.task_id, None)
        self._core.task_manager.fail_or_retry(
            spec, err, resubmit=self.submit)

    def _resubmit_bounced(self, spec: TaskSpec):
        """Timer-thread re-lease of a transiently bounced task.  A
        cluster torn down while the timer was pending must not be
        resubmitted into (the re-lease would bounce-loop against dead
        raylets across later tests in the same process)."""
        raylet = self._core.local_raylet
        if raylet is None or getattr(raylet, "_dead", False):
            return
        self.submit(spec)

    # ---- dispatch -------------------------------------------------------
    def _push(self, spec: TaskSpec, worker, raylet, key: int):
        from ray_tpu.gcs import task_events
        nid = getattr(worker, "node_id", None)
        wid = getattr(worker, "worker_id", None)
        task_events.emit(self._core.cluster, spec.task_id,
                         task_events.SUBMITTED_TO_WORKER,
                         node_id=nid.hex() if nid is not None else "",
                         worker_id=wid.hex() if wid is not None else "")

        def on_done(error):
            if error is None:
                self._core.task_manager.complete_task(spec)
                self._on_worker_idle(worker, raylet, key)
            else:
                # User errors don't poison the worker; system errors do.
                if isinstance(error, exceptions.TaskError):
                    self._on_worker_idle(worker, raylet, key)
                else:
                    raylet.return_worker(worker, disconnect=True)
                retried = self._core.task_manager.fail_or_retry(
                    spec, error, resubmit=self.submit)
                _ = retried

        worker.push_task(spec, on_done)

    def _on_worker_idle(self, worker, raylet, key: int):
        """Reuse the leased worker for the next queued task of this class
        (OnWorkerIdle, direct_task_transport.cc:157)."""
        with self._lock:
            state = self._keys[key]
            if state.queue:
                spec = state.queue.popleft()
                self._push(spec, worker, raylet, key)
                return
            # No more work: return the lease.
        raylet.return_worker(worker)
