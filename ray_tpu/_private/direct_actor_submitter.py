"""Actor-task transport: ordered per-actor queues + restart handling.

Parity: reference
``src/ray/core_worker/transport/direct_actor_task_submitter.h`` — per-actor
sequenced submit queue (``sequential_actor_submit_queue.cc``; out-of-order
variant when ``max_concurrency>1``), queue paused while the actor is
RESTARTING, tasks failed with ``ActorError`` once the actor is DEAD
(``GcsActorManager`` restart orchestration).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict

from ray_tpu import exceptions
from ray_tpu._private.ids import ActorID
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.gcs.actor_manager import ActorState
from ray_tpu._private.debug import diag_rlock


class _ActorQueue:
    __slots__ = ("pending", "inflight", "state", "worker")

    def __init__(self):
        self.pending: deque = deque()
        self.inflight = 0
        self.state = ActorState.PENDING_CREATION
        self.worker = None


class DirectActorTaskSubmitter:
    def __init__(self, core_worker):
        self._core = core_worker
        self._lock = diag_rlock("DirectActorSubmitter._lock")
        self._queues: Dict[ActorID, _ActorQueue] = {}

    def _queue_for(self, actor_id: ActorID) -> _ActorQueue:
        q = self._queues.get(actor_id)
        if q is None:
            q = _ActorQueue()
            self._queues[actor_id] = q
            # Track actor state via GCS pubsub (actor channel).
            from ray_tpu.gcs import pubsub as pubsub_mod
            self._core.cluster.gcs.publisher.subscribe(
                pubsub_mod.ACTOR_CHANNEL, actor_id.binary(),
                lambda key, info, aid=actor_id: self._on_actor_update(aid, info))
            # Seed current state.
            actor = self._core.cluster.gcs.actor_manager.get_actor(actor_id)
            if actor is not None:
                q.state = actor.state
                q.worker = actor.worker
        return q

    def submit(self, spec: TaskSpec):
        actor_id = spec.actor_id
        with self._lock:
            q = self._queue_for(actor_id)
            q.pending.append(spec)
        self._pump(actor_id)

    def _pump(self, actor_id: ActorID):
        while True:
            with self._lock:
                q = self._queues.get(actor_id)
                if q is None or not q.pending:
                    return
                if q.state == ActorState.DEAD:
                    spec = q.pending.popleft()
                    actor = self._core.cluster.gcs.actor_manager.get_actor(
                        actor_id)
                    reason = actor.death_cause if actor else "actor dead"
                    err = exceptions.ActorDiedError(actor_id, reason)
                    fail = True
                elif q.state == ActorState.ALIVE and q.worker is not None:
                    spec = q.pending.popleft()
                    q.inflight += 1
                    worker = q.worker
                    fail = False
                else:
                    return  # PENDING/RESTARTING: hold the queue.
            if fail:
                self._core.task_manager.fail_task(spec, err)
                continue

            def on_done(error, spec=spec, worker=worker):
                with self._lock:
                    q2 = self._queues.get(actor_id)
                    if q2 is not None:
                        q2.inflight -= 1
                if error is None:
                    self._core.task_manager.complete_task(spec)
                elif isinstance(error, exceptions.TaskError):
                    self._core.task_manager.fail_task(spec, error)
                else:
                    # Worker/system failure: the GCS will restart or kill
                    # the actor; retry per max_task_retries.
                    self._core.task_manager.fail_or_retry(
                        spec, error, resubmit=self.submit)
                self._pump(actor_id)

            from ray_tpu.gcs import task_events
            nid = getattr(worker, "node_id", None)
            wid = getattr(worker, "worker_id", None)
            task_events.emit(self._core.cluster, spec.task_id,
                             task_events.SUBMITTED_TO_WORKER,
                             node_id=nid.hex() if nid is not None else "",
                             worker_id=wid.hex() if wid is not None else "")
            worker.submit_actor_task(spec, on_done)

    def on_gcs_restart(self):
        """Re-home every live per-actor queue onto the restarted GCS:
        pubsub subscriptions died with the old publisher, and the worker
        handles must be re-read from the reconciled actor registry."""
        from ray_tpu.gcs import pubsub as pubsub_mod
        with self._lock:
            actor_ids = list(self._queues)
        gcs = self._core.cluster.gcs
        for actor_id in actor_ids:
            gcs.publisher.subscribe(
                pubsub_mod.ACTOR_CHANNEL, actor_id.binary(),
                lambda key, info, aid=actor_id:
                self._on_actor_update(aid, info))
            actor = gcs.actor_manager.get_actor(actor_id)
            with self._lock:
                q = self._queues.get(actor_id)
                if q is not None and actor is not None:
                    q.state = actor.state
                    q.worker = actor.worker
            self._pump(actor_id)

    def _on_actor_update(self, actor_id: ActorID, info: dict):
        actor = self._core.cluster.gcs.actor_manager.get_actor(actor_id)
        with self._lock:
            q = self._queues.get(actor_id)
            if q is None:
                return
            q.state = info.get("state", q.state)
            q.worker = actor.worker if actor is not None else None
        self._pump(actor_id)
