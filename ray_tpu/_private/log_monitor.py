"""Worker log capture + streaming to the driver.

Parity: reference ``python/ray/_private/log_monitor.py`` — every node
runs a log monitor that tails its workers' stdout/stderr files and
publishes new lines to GCS pubsub; drivers subscribe and re-print the
lines with a worker prefix, which is how a ``print()`` inside a task
running in another OS process shows up on the driver's terminal.

Here the worker-host that spawns a process worker opens
``<temp_dir>/logs/host-<pid>/worker-<id>.{out,err}`` for the child
(``worker_pool.py`` wires them into Popen), and a ``LogMonitor`` thread
in that host tails the directory.  In the in-process cluster the
monitor publishes straight into the GCS publisher; a ``NodeHost``
publishes through its wire client (``publish_log`` on the head
service).  The driver mirror (``mirror_worker_logs``) subscribes to
the ``worker_logs`` channel.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Callable, Dict, Optional

from ray_tpu._private.config import get_config

LOG_CHANNEL = "worker_logs"


def worker_log_dir(create: bool = True) -> str:
    """This host process's worker-log directory.  Keyed by pid: each
    worker-host (driver process, NodeHost) owns one directory on its
    machine, like the reference's per-node session logs dir."""
    d = os.path.join(get_config().temp_dir, "logs", f"host-{os.getpid()}")
    if create:
        os.makedirs(d, exist_ok=True)
    return d


def open_worker_log_files(worker_id_hex: str):
    """(stdout, stderr) file objects for a spawning worker process."""
    d = worker_log_dir()
    out = open(os.path.join(d, f"worker-{worker_id_hex}.out"), "ab")
    err = open(os.path.join(d, f"worker-{worker_id_hex}.err"), "ab")
    return out, err


class LogMonitor:
    """Tails every ``worker-*.{out,err}`` file in this host's log dir
    and ships complete new lines through ``publish(payload)``.

    ``payload`` = ``{"worker": <id hex>, "is_err": bool,
    "lines": [str, ...], "host_pid": int}``.
    """

    def __init__(self, publish: Callable[[dict], None],
                 poll_interval_s: float = 0.2):
        self._publish = publish
        self._poll = poll_interval_s
        self._dir = worker_log_dir()
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ray_tpu::log_monitor")
        self._thread.start()

    def _loop(self):
        from ray_tpu._private.debug import swallow
        while not self._stop.is_set():
            try:
                self.scan_once()
            except Exception as e:
                swallow.noted("log_monitor.scan", e)
            self._stop.wait(self._poll)

    def scan_once(self):
        try:
            names = os.listdir(self._dir)
        except OSError:
            return
        for name in names:
            if not (name.startswith("worker-")
                    and (name.endswith(".out") or name.endswith(".err"))):
                continue
            path = os.path.join(self._dir, name)
            off = self._offsets.get(path, 0)
            try:
                size = os.path.getsize(path)
                if size <= off:
                    continue
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(size - off)
            except OSError:
                continue
            # Ship only complete lines; a partial trailing line stays
            # unconsumed until its newline arrives.
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                continue
            self._offsets[path] = off + last_nl + 1
            lines = chunk[:last_nl].decode("utf-8", "replace").split("\n")
            worker = name[len("worker-"):].rsplit(".", 1)[0]
            self._publish({"worker": worker,
                           "is_err": name.endswith(".err"),
                           "lines": lines,
                           "host_pid": os.getpid()})

    def stop(self):
        self._stop.set()
        # The poll thread may be mid-scan; _offsets is unsynchronized,
        # so wait it out before the final sweep (else the same chunk
        # ships twice).
        self._thread.join(timeout=5.0)
        # Final sweep so lines written just before stop still ship.
        try:
            self.scan_once()
        except Exception:
            pass


def start_local_monitor(publisher) -> LogMonitor:
    """Monitor for the in-process cluster: publishes straight into the
    GCS publisher (reference: log monitor -> GCS pubsub)."""
    def publish(payload: dict):
        publisher.publish(LOG_CHANNEL,
                          payload["worker"].encode(), payload)
    return LogMonitor(publish)


# One monitor per OS process: the log dir is keyed by pid, so a second
# tailer (multi-node in-process cluster = one WorkerPool per node) would
# re-publish every line.  Refcounted so the first pool to shut down
# doesn't silence the others.
_local_lock = threading.Lock()
_local_monitor: Optional[LogMonitor] = None
_local_refs = 0


def acquire_local_monitor(publisher) -> None:
    global _local_monitor, _local_refs
    with _local_lock:
        if _local_monitor is None:
            _local_monitor = start_local_monitor(publisher)
        _local_refs += 1


def release_local_monitor() -> None:
    global _local_monitor, _local_refs
    with _local_lock:
        if _local_refs == 0:
            return
        _local_refs -= 1
        if _local_refs > 0:
            return
        monitor, _local_monitor = _local_monitor, None
    if monitor is not None:
        monitor.stop()


def make_log_mirror_callback(out=None, err=None):
    """The driver-side mirror: prints a published worker log message
    with a ``(worker=..., pid=...)`` prefix (reference worker.py
    print_worker_logs).  Shared by in-process subscriptions and the
    remote driver's long-poll subscriber."""

    def cb(_key, msg):
        try:
            stream = (err or sys.stderr) if msg.get("is_err") \
                else (out or sys.stdout)
            prefix = f"(worker={msg.get('worker', '')[:8]} " \
                     f"pid={msg.get('host_pid', '?')})"
            for line in msg.get("lines", ()):
                print(f"{prefix} {line}", file=stream, flush=True)
        except Exception:
            pass

    return cb


def mirror_worker_logs(publisher, out=None, err=None) -> int:
    """In-process driver: subscribe the mirror to the GCS publisher."""
    return publisher.subscribe(LOG_CHANNEL, None,
                               make_log_mirror_callback(out, err))
