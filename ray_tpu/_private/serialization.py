"""Object serialization for the store and task-arg path.

TPU-native equivalent of the reference serializer
(``python/ray/serialization.py:413`` — cloudpickle for code/closures,
zero-copy numpy via pickle-protocol-5 out-of-band buffers, nested
``ObjectRef`` capture for distributed refcounting).

Design points kept from the reference:
  * values are immutable once stored — we serialize on ``put`` so later
    mutation of the Python object cannot leak into the store;
  * numpy / jax host buffers go out-of-band (no copy into the pickle
    stream), and deserialization reconstructs views over the stored
    buffers — the zero-copy read path;
  * ``ObjectRef``\\s contained in a value are collected during
    serialization so the owner can register borrows
    (reference: ``serialization.py`` ``_make_serialization_context`` +
    reference_count borrowing protocol).
"""

from __future__ import annotations

import io
import os
import pickle
import threading
from typing import Any, List, Optional

import cloudpickle

#: Buffers at or above this size go through np.copyto (4x the
#: throughput of CPython memoryview slice assignment, and it releases
#: the GIL); at or above _PARALLEL_COPY_MIN they are additionally
#: striped across copy threads (a single memcpy stream is
#: memory-bandwidth bound; 2+ streams help on multi-channel hosts).
_NUMPY_COPY_MIN = 256 * 1024
_PARALLEL_COPY_MIN = 64 * 1024 * 1024
_COPY_STRIPES = max(2, int(os.environ.get("RAY_TPU_COPY_STRIPES", "4")))
_copy_pool = None
_copy_pool_lock = threading.Lock()

#: Cumulative payload bytes memcpy'd by :func:`copy_into_view` — the
#: data plane's copy ledger.  The copy-count regression tests read this
#: to prove the put path moves each payload byte at most once.
copy_stats = {"bytes_copied": 0, "copies": 0}


def _get_copy_pool():
    global _copy_pool
    if _copy_pool is None:
        with _copy_pool_lock:
            if _copy_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _copy_pool = ThreadPoolExecutor(
                    max_workers=_COPY_STRIPES,
                    thread_name_prefix="ray_tpu::copy")
    return _copy_pool


def copy_into_view(dst: memoryview, offset: int, src) -> int:
    """Copy ``src`` (buffer-protocol object) into ``dst[offset:]``.

    The data plane's ONE allowed copy: large contiguous buffers go
    through striped ``np.copyto`` calls (numpy releases the GIL for
    bulk copies, so stripes overlap on multi-core hosts); small ones
    use plain memoryview assignment.  Returns bytes written."""
    mv = src if isinstance(src, memoryview) else memoryview(src)
    n = mv.nbytes
    copy_stats["bytes_copied"] += n
    copy_stats["copies"] += 1
    if n >= _NUMPY_COPY_MIN and mv.contiguous:
        try:
            import numpy as np
            d = np.frombuffer(dst, dtype=np.uint8, count=n, offset=offset)
            s = np.frombuffer(mv.cast("B"), dtype=np.uint8)
            if n < _PARALLEL_COPY_MIN:
                np.copyto(d, s)
            else:
                step = (n + _COPY_STRIPES - 1) // _COPY_STRIPES
                bounds = [(i, min(i + step, n)) for i in range(0, n, step)]
                list(_get_copy_pool().map(
                    lambda b: np.copyto(d[b[0]:b[1]], s[b[0]:b[1]]),
                    bounds))
            return n
        except Exception:
            pass  # fall through to the plain path
    if not (mv.ndim == 1 and mv.format == "B"):
        mv = mv.cast("B") if mv.contiguous else memoryview(bytes(mv))
    dst[offset:offset + n] = mv
    return n


class SerializedObject:
    """An immutable serialized value: inband pickle bytes + raw buffers."""

    __slots__ = ("inband", "buffers", "contained_refs", "metadata",
                 "_header")

    def __init__(self, inband: bytes, buffers: List[memoryview],
                 contained_refs: list, metadata: bytes = b""):
        self.inband = inband
        self.buffers = buffers
        self.contained_refs = contained_refs
        self.metadata = metadata
        self._header = None

    @property
    def total_bytes(self) -> int:
        return len(self.inband) + sum(b.nbytes for b in self.buffers)

    def _flat_header(self) -> bytes:
        if self._header is None:
            self._header = pickle.dumps(
                (len(self.inband), [b.nbytes for b in self.buffers]),
                protocol=5)
        return self._header

    @property
    def flat_nbytes(self) -> int:
        """Size of the flattened wire/segment form (``to_bytes`` length)."""
        return 8 + len(self._flat_header()) + self.total_bytes

    def write_into(self, dst: memoryview) -> int:
        """Write the flattened form directly into ``dst`` — THE single
        data copy of the put path (segment memory, a transfer buffer, a
        spill file mmap).  Layout is identical to :meth:`to_bytes`.
        Returns bytes written."""
        header = self._flat_header()
        hlen = len(header)
        dst[0:8] = hlen.to_bytes(8, "little")
        dst[8:8 + hlen] = header
        off = 8 + hlen
        dst[off:off + len(self.inband)] = self.inband
        off += len(self.inband)
        for b in self.buffers:
            off += copy_into_view(dst, off, b)
        return off

    def to_bytes(self) -> bytes:
        """Flatten to a single contiguous blob (for spilling / transfer)."""
        out = bytearray(self.flat_nbytes)
        self.write_into(memoryview(out))
        return bytes(out)

    def __reduce__(self):
        # Cross-process wire path (task specs carry inline args as
        # SerializedObject): flatten to one blob — memoryview buffers are
        # not themselves picklable.  Contained refs are not re-captured;
        # the owner registered them at submission time.
        return (SerializedObject.from_bytes, (self.to_bytes(),))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "SerializedObject":
        hlen = int.from_bytes(blob[:8], "little")
        inband_len, buf_lens = pickle.loads(blob[8:8 + hlen])
        off = 8 + hlen
        inband = blob[off:off + inband_len]
        off += inband_len
        buffers = []
        mv = memoryview(blob)
        for n in buf_lens:
            buffers.append(mv[off:off + n])
            off += n
        return cls(inband, buffers, [])


_thread_local = threading.local()


def _is_object_ref(obj) -> bool:
    # Late import to avoid a cycle; ObjectRef lives in object_ref.py.
    from ray_tpu._private.object_ref import ObjectRef
    return isinstance(obj, ObjectRef)


class _Pickler(cloudpickle.CloudPickler):
    """Cloudpickle with out-of-band buffer capture and ref collection."""

    def __init__(self, file, buffers_out, refs_out):
        super().__init__(file, protocol=5,
                         buffer_callback=lambda b: buffers_out.append(b) or False)
        self._refs_out = refs_out

    def persistent_id(self, obj):
        return None

    def reducer_override(self, obj):
        if _is_object_ref(obj):
            self._refs_out.append(obj)
            return (_deserialize_ref_placeholder,
                    (obj.binary(), obj.owner_id_binary()))
        return super().reducer_override(obj)


def _deserialize_ref_placeholder(binary: bytes, owner_binary):
    from ray_tpu._private.object_ref import ObjectRef
    from ray_tpu._private.ids import ObjectID, WorkerID
    owner = WorkerID(owner_binary) if owner_binary else None
    ref = ObjectRef(ObjectID(binary), owner_id=owner, skip_adding_local_ref=False)
    return ref


def serialize(value: Any) -> SerializedObject:
    """Serialize ``value`` with zero-copy buffer capture.

    Numpy arrays (and anything exporting pickle-5 buffers) contribute
    out-of-band ``memoryview`` buffers; jax device arrays are brought to host
    as numpy first (device residency is handled one level up by the
    device-object extension in the object store).
    """
    value = _device_to_host(value)
    buffers: List[pickle.PickleBuffer] = []
    refs: list = []
    f = io.BytesIO()
    _Pickler(f, buffers, refs).dump(value)
    views = [b.raw() for b in buffers]
    return SerializedObject(f.getvalue(), views, refs)


def deserialize(s: SerializedObject) -> Any:
    return pickle.loads(s.inband, buffers=[bytes(b) if isinstance(b, memoryview)
                                           and not b.contiguous else b
                                           for b in s.buffers])


def _device_to_host(value):
    """Convert jax arrays to numpy on serialization boundaries.

    jax arrays are XLA-managed device buffers; passing them through the host
    object store requires a device->host copy.  Actor-to-actor device handoff
    avoids this path entirely (see object_store.DeviceObject).
    """
    import sys
    jax = sys.modules.get("jax")
    # getattr, not attribute access: another thread may be mid-way
    # through the first `import jax` (e.g. the scheduler's jax backend
    # loading on its own thread), leaving a partially-initialized
    # module in sys.modules without `Array` yet.  A value can only BE a
    # jax array if jax finished importing wherever it was created.
    jax_array = getattr(jax, "Array", None)
    if jax_array is not None and isinstance(value, jax_array):
        import numpy as np
        return np.asarray(value)
    return value


def serialize_into(value: Any, writer):
    """Serialize ``value`` straight into writer-provided memory.

    The single-copy put path: pickling captures out-of-band buffer
    VIEWS (no copy), the writer reserves ``flat_nbytes`` of destination
    memory (a shm-segment block, a transfer buffer, a tracking stub),
    and :meth:`SerializedObject.write_into` moves each payload byte
    exactly once, source -> destination.  No intermediate ``bytes`` is
    ever materialized.  The worker-process return path rides this
    (worker_main._ShmReturnWriter).

    Writer protocol::

        reserve(nbytes) -> memoryview | None   # None = cannot take it
        commit(serialized, nbytes) -> bool     # False = commit failed
        abort(exc)                             # failed mid-write

    Returns ``(serialized, delivered)``: the
    :class:`SerializedObject` metadata (buffers still reference the
    SOURCE — serialization is never repeated), and whether the value
    actually landed in the writer's memory.  ``delivered=False``
    (declined reservation, write failure, failed commit) means the
    caller must ship ``serialized`` through its fallback path."""
    s = serialize(value)
    nbytes = s.flat_nbytes
    dst = writer.reserve(nbytes)
    if dst is None:
        return s, False
    try:
        s.write_into(dst)
    except BaseException as e:  # noqa: BLE001 — fall back after abort
        writer.abort(e)
        return s, False
    return s, bool(writer.commit(s, nbytes))


def dumps_function(fn) -> bytes:
    """Pickle user code/closures (reference: function_manager export path)."""
    return cloudpickle.dumps(fn)


def loads_function(blob: bytes):
    return cloudpickle.loads(blob)
