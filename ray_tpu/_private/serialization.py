"""Object serialization for the store and task-arg path.

TPU-native equivalent of the reference serializer
(``python/ray/serialization.py:413`` — cloudpickle for code/closures,
zero-copy numpy via pickle-protocol-5 out-of-band buffers, nested
``ObjectRef`` capture for distributed refcounting).

Design points kept from the reference:
  * values are immutable once stored — we serialize on ``put`` so later
    mutation of the Python object cannot leak into the store;
  * numpy / jax host buffers go out-of-band (no copy into the pickle
    stream), and deserialization reconstructs views over the stored
    buffers — the zero-copy read path;
  * ``ObjectRef``\\s contained in a value are collected during
    serialization so the owner can register borrows
    (reference: ``serialization.py`` ``_make_serialization_context`` +
    reference_count borrowing protocol).
"""

from __future__ import annotations

import io
import pickle
import threading
from typing import Any, List, Tuple

import cloudpickle


class SerializedObject:
    """An immutable serialized value: inband pickle bytes + raw buffers."""

    __slots__ = ("inband", "buffers", "contained_refs", "metadata")

    def __init__(self, inband: bytes, buffers: List[memoryview],
                 contained_refs: list, metadata: bytes = b""):
        self.inband = inband
        self.buffers = buffers
        self.contained_refs = contained_refs
        self.metadata = metadata

    @property
    def total_bytes(self) -> int:
        return len(self.inband) + sum(b.nbytes for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten to a single contiguous blob (for spilling / transfer)."""
        out = io.BytesIO()
        header = pickle.dumps(
            (len(self.inband), [b.nbytes for b in self.buffers]), protocol=5)
        out.write(len(header).to_bytes(8, "little"))
        out.write(header)
        out.write(self.inband)
        for b in self.buffers:
            out.write(b)
        return out.getvalue()

    def __reduce__(self):
        # Cross-process wire path (task specs carry inline args as
        # SerializedObject): flatten to one blob — memoryview buffers are
        # not themselves picklable.  Contained refs are not re-captured;
        # the owner registered them at submission time.
        return (SerializedObject.from_bytes, (self.to_bytes(),))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "SerializedObject":
        hlen = int.from_bytes(blob[:8], "little")
        inband_len, buf_lens = pickle.loads(blob[8:8 + hlen])
        off = 8 + hlen
        inband = blob[off:off + inband_len]
        off += inband_len
        buffers = []
        mv = memoryview(blob)
        for n in buf_lens:
            buffers.append(mv[off:off + n])
            off += n
        return cls(inband, buffers, [])


_thread_local = threading.local()


def _is_object_ref(obj) -> bool:
    # Late import to avoid a cycle; ObjectRef lives in object_ref.py.
    from ray_tpu._private.object_ref import ObjectRef
    return isinstance(obj, ObjectRef)


class _Pickler(cloudpickle.CloudPickler):
    """Cloudpickle with out-of-band buffer capture and ref collection."""

    def __init__(self, file, buffers_out, refs_out):
        super().__init__(file, protocol=5,
                         buffer_callback=lambda b: buffers_out.append(b) or False)
        self._refs_out = refs_out

    def persistent_id(self, obj):
        return None

    def reducer_override(self, obj):
        if _is_object_ref(obj):
            self._refs_out.append(obj)
            return (_deserialize_ref_placeholder,
                    (obj.binary(), obj.owner_id_binary()))
        return super().reducer_override(obj)


def _deserialize_ref_placeholder(binary: bytes, owner_binary):
    from ray_tpu._private.object_ref import ObjectRef
    from ray_tpu._private.ids import ObjectID, WorkerID
    owner = WorkerID(owner_binary) if owner_binary else None
    ref = ObjectRef(ObjectID(binary), owner_id=owner, skip_adding_local_ref=False)
    return ref


def serialize(value: Any) -> SerializedObject:
    """Serialize ``value`` with zero-copy buffer capture.

    Numpy arrays (and anything exporting pickle-5 buffers) contribute
    out-of-band ``memoryview`` buffers; jax device arrays are brought to host
    as numpy first (device residency is handled one level up by the
    device-object extension in the object store).
    """
    value = _device_to_host(value)
    buffers: List[pickle.PickleBuffer] = []
    refs: list = []
    f = io.BytesIO()
    _Pickler(f, buffers, refs).dump(value)
    views = [b.raw() for b in buffers]
    return SerializedObject(f.getvalue(), views, refs)


def deserialize(s: SerializedObject) -> Any:
    return pickle.loads(s.inband, buffers=[bytes(b) if isinstance(b, memoryview)
                                           and not b.contiguous else b
                                           for b in s.buffers])


def _device_to_host(value):
    """Convert jax arrays to numpy on serialization boundaries.

    jax arrays are XLA-managed device buffers; passing them through the host
    object store requires a device->host copy.  Actor-to-actor device handoff
    avoids this path entirely (see object_store.DeviceObject).
    """
    import sys
    jax = sys.modules.get("jax")
    # getattr, not attribute access: another thread may be mid-way
    # through the first `import jax` (e.g. the scheduler's jax backend
    # loading on its own thread), leaving a partially-initialized
    # module in sys.modules without `Array` yet.  A value can only BE a
    # jax array if jax finished importing wherever it was created.
    jax_array = getattr(jax, "Array", None)
    if jax_array is not None and isinstance(value, jax_array):
        import numpy as np
        return np.asarray(value)
    return value


def dumps_function(fn) -> bytes:
    """Pickle user code/closures (reference: function_manager export path)."""
    return cloudpickle.dumps(fn)


def loads_function(blob: bytes):
    return cloudpickle.loads(blob)
