"""Function/actor-class export via the GCS KV.

Parity: reference ``python/ray/_private/function_manager.py`` — user
functions are cloudpickled once per definition, exported to the GCS KV keyed
by a content hash, and loaded+cached on the executor side.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable, Dict

from ray_tpu._private.debug.lock_order import diag_lock
from ray_tpu._private.ids import FunctionID
from ray_tpu._private.serialization import dumps_function, loads_function

_KV_PREFIX = b"fn:"


class FunctionManager:
    def __init__(self, kv):
        self._kv = kv
        self._lock = diag_lock("FunctionManager._lock")
        # id(fn) -> (FunctionID, weakref-to-fn); the weakref guards
        # against id() reuse after the original function is collected.
        self._export_cache: Dict[int, tuple] = {}
        self._load_cache: Dict[FunctionID, Callable] = {}

    def export(self, fn: Callable) -> FunctionID:
        import weakref
        key = id(fn)
        with self._lock:
            cached = self._export_cache.get(key)
            # id() values are reused after GC: a dead closure's address
            # can be handed to a brand-new function, which would then
            # silently execute the OLD function's code.  The weakref
            # identity check makes the cache hit only for the live
            # original.
            if cached is not None and cached[1]() is fn:
                return cached[0]
        blob = dumps_function(fn)
        digest = hashlib.sha256(blob).digest()[:FunctionID.SIZE]
        function_id = FunctionID(digest)
        self._kv.put(_KV_PREFIX + function_id.binary(), blob, overwrite=False)
        try:
            ref = weakref.ref(fn)
        except TypeError:
            ref = lambda _f=fn: _f     # non-weakrefable: strong pin
        with self._lock:
            self._export_cache[key] = (function_id, ref)
            # Seed the load cache with the original callable so local
            # execution avoids a deserialize round-trip.
            self._load_cache.setdefault(function_id, fn)
        return function_id

    def load(self, function_id: FunctionID) -> Callable:
        with self._lock:
            fn = self._load_cache.get(function_id)
        if fn is not None:
            return fn
        blob = self._kv.get(_KV_PREFIX + function_id.binary())
        if blob is None:
            raise KeyError(f"Function {function_id} not found in GCS KV")
        fn = loads_function(blob)
        with self._lock:
            self._load_cache[function_id] = fn
        return fn
