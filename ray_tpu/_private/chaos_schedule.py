"""Seeded, reproducible chaos schedules for the cluster envelope.

The envelope soak (``tools/envelope.py`` / ``ray-tpu envelope``) drives
a 50–64-host fleet through its full workload while THIS module keeps
faults firing underneath it: asymmetric partitions, SIGKILLs, RPC
delays/duplicates, and spill faults, all generated from one integer
seed so a failing soak replays bit-identically (``generate_schedule``
is a pure function of its arguments — the determinism is pinned by
``tests/test_envelope.py``).

Two halves:

* :func:`generate_schedule` — seed → ``List[ChaosEvent]``, sorted by
  fire time.  No wall clock, no randomness source but the seed.
* :class:`ChaosRunner` — a background thread that walks the schedule
  against a LIVE fleet, applying each event through the PR 14 wire
  fault plane (``fault_injection.partition`` / ``arm_over_wire`` over
  the fault-exempt control verbs) and PR 6 fault points
  (``spill.write``), and healing timed events when their duration
  elapses.  Every application lands in ``event_log`` with its outcome
  — a chaos run whose faults never fired proves nothing, so the log is
  the envelope's evidence, not a debugging convenience.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, List, Optional

from ray_tpu._private import fault_injection
from ray_tpu._private.debug import swallow

#: Everything generate_schedule can emit.  ``partition`` carries a
#: direction (inbound / outbound / both — one direction alone is the
#: classic asymmetric, zombie-producing shape) and a duration; some
#: durations deliberately land INSIDE the suspect grace so the run
#: proves sub-grace flaps cause zero restarts.
KINDS = ("partition", "sigkill", "rpc_delay", "rpc_duplicate",
         "spill_fault")


@dataclasses.dataclass
class ChaosEvent:
    at_s: float             # fire time, seconds from schedule start
    kind: str               # one of KINDS
    target: int             # fleet index (runner resolves mod fleet size)
    duration_s: float = 0.0  # timed events heal this long after firing
    params: dict = dataclasses.field(default_factory=dict)

    def to_jsonable(self) -> dict:
        return dataclasses.asdict(self)


def generate_schedule(seed: int, duration_s: float, n_events: int,
                      n_targets: int,
                      kinds: Optional[List[str]] = None,
                      kill_budget: Optional[int] = None,
                      flap_band=(0.3, 0.9),
                      hold_band=(1.5, 4.0)) -> List[ChaosEvent]:
    """Deterministic fault timeline: same arguments, same schedule.

    ``kill_budget`` bounds SIGKILLs (default ``max(1, n_targets //
    16)``) so the fleet survives its own soak; partition durations draw
    from ``flap_band`` (sub-grace flap — must cause zero restarts) or
    ``hold_band`` (past suspect, sometimes past dead) with equal
    probability."""
    rng = random.Random(seed)
    kinds = list(kinds) if kinds else list(KINDS)
    if kill_budget is None:
        kill_budget = max(1, n_targets // 16)
    kills = 0
    events: List[ChaosEvent] = []
    for _ in range(n_events):
        at = rng.uniform(0.05 * duration_s, 0.95 * duration_s)
        kind = rng.choice(kinds)
        if kind == "sigkill" and kills >= kill_budget:
            kind = "partition"
        # Target 0 is reserved by convention for the envelope's relay
        # origin / first node: chaos may partition it but not kill it.
        target = rng.randrange(1, max(2, n_targets))
        if kind == "partition":
            direction = rng.choice(("inbound", "outbound", "both"))
            band = flap_band if rng.random() < 0.5 else hold_band
            dur = rng.uniform(*band)
            events.append(ChaosEvent(at, kind, target, dur,
                                     {"direction": direction}))
        elif kind == "sigkill":
            kills += 1
            events.append(ChaosEvent(at, kind, target))
        elif kind == "rpc_delay":
            events.append(ChaosEvent(
                at, kind, target, 0.0,
                {"delay_s": round(rng.uniform(0.05, 0.3), 3),
                 "count": rng.randrange(5, 50)}))
        elif kind == "rpc_duplicate":
            events.append(ChaosEvent(
                at, kind, target, 0.0,
                {"count": rng.randrange(3, 20)}))
        elif kind == "spill_fault":
            events.append(ChaosEvent(
                at, kind, target, 0.0,
                {"count": rng.randrange(1, 4)}))
        else:
            raise ValueError(f"unknown chaos kind {kind!r}")
    events.sort(key=lambda e: e.at_s)
    return events


class ChaosRunner:
    """Walk a schedule against a live fleet on a background thread.

    ``handles`` are :class:`~ray_tpu._private.cluster.RemoteNodeHandle`
    rows; an event's ``target`` indexes into them (mod size).  Events
    targeting an already-killed node are logged as skipped, not
    silently dropped.  ``stop()`` heals every armed partition — the
    runner must never leave the cluster partitioned after the workload
    finished, or teardown itself wedges."""

    def __init__(self, handles, schedule: List[ChaosEvent],
                 on_event: Optional[Callable] = None):
        self._handles = list(handles)
        self._schedule = sorted(schedule, key=lambda e: e.at_s)
        self._on_event = on_event
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._active: List[tuple] = []   # (heal_at_abs, partition, row)
        self._dead: set = set()          # fleet indexes SIGKILLed
        self.event_log: List[dict] = []
        self.events_fired = 0
        self.events_skipped = 0

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> "ChaosRunner":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ray_tpu::chaos")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self._heal_all()

    # ---- the walk ------------------------------------------------------
    def _run(self):
        t0 = time.monotonic()
        for ev in self._schedule:
            while not self._stop.is_set():
                now = time.monotonic() - t0
                self._heal_due(now)
                if now >= ev.at_s:
                    break
                self._stop.wait(min(0.05, ev.at_s - now))
            if self._stop.is_set():
                break
            self._apply(ev, time.monotonic() - t0)
        # Drain remaining heals so timed events still close out.
        while not self._stop.is_set() and self._active:
            self._heal_due(time.monotonic() - t0)
            self._stop.wait(0.05)

    def _resolve(self, ev: ChaosEvent):
        idx = ev.target % len(self._handles)
        h = self._handles[idx]
        if idx in self._dead or h.proc.poll() is not None:
            return idx, h, None
        return idx, h, h.proxy

    def _apply(self, ev: ChaosEvent, now_s: float):
        idx, handle, proxy = self._resolve(ev)
        row = {"at_s": round(ev.at_s, 3), "applied_s": round(now_s, 3),
               "kind": ev.kind, "target": idx,
               "node": handle.node_name, "params": dict(ev.params),
               "duration_s": round(ev.duration_s, 3)}
        try:
            if proxy is None and ev.kind != "sigkill":
                row["outcome"] = "skipped: target dead"
                self.events_skipped += 1
                self.event_log.append(row)
                return
            if ev.kind == "partition":
                direction = ev.params.get("direction", "both")
                p = fault_injection.partition(
                    tuple(proxy.address),
                    outbound=direction in ("outbound", "both"),
                    inbound=direction in ("inbound", "both"))
                p.arm()
                self._active.append((ev.at_s + ev.duration_s, p, row))
                row["outcome"] = "armed"
            elif ev.kind == "sigkill":
                if idx in self._dead:
                    row["outcome"] = "skipped: already dead"
                    self.events_skipped += 1
                    self.event_log.append(row)
                    return
                handle.kill()
                self._dead.add(idx)
                row["outcome"] = "killed"
            elif ev.kind == "rpc_delay":
                fault_injection.arm_over_wire(
                    proxy.client, "rpc.send", "delay",
                    count=int(ev.params.get("count", 10)),
                    delay_s=float(ev.params.get("delay_s", 0.1)))
                row["outcome"] = "armed"
            elif ev.kind == "rpc_duplicate":
                fault_injection.arm_over_wire(
                    proxy.client, "rpc.send", "duplicate",
                    count=int(ev.params.get("count", 5)))
                row["outcome"] = "armed"
            elif ev.kind == "spill_fault":
                fault_injection.arm_over_wire(
                    proxy.client, "spill.write", "error",
                    count=int(ev.params.get("count", 1)))
                row["outcome"] = "armed"
            else:
                row["outcome"] = f"skipped: unknown kind {ev.kind!r}"
                self.events_skipped += 1
                self.event_log.append(row)
                return
            self.events_fired += 1
        except Exception as e:
            # A fault that failed to arm (target mid-death, wire race)
            # is an explicit log row — the soak's evidence must show
            # what actually fired, not what was scheduled.
            swallow.noted("chaos.apply", e)
            row["outcome"] = f"error: {type(e).__name__}: {e}"
            self.events_skipped += 1
        self.event_log.append(row)
        if self._on_event is not None:
            try:
                self._on_event(row)
            except Exception as e:
                swallow.noted("chaos.on_event", e)

    def _heal_due(self, now_s: float):
        due = [a for a in self._active if a[0] <= now_s]
        self._active = [a for a in self._active if a[0] > now_s]
        for _heal_at, p, row in due:
            self._heal_one(p, row, now_s)

    def _heal_all(self):
        active, self._active = self._active, []
        for _heal_at, p, row in active:
            self._heal_one(p, row, None)

    def _heal_one(self, p, row: dict, now_s: Optional[float]):
        try:
            # heal() disarms the drop faults inside the daemon; close()
            # only releases the helper's own control-channel client.
            # Calling close() alone leaves the partition armed FOREVER
            # — sub-grace flaps silently escalate to node deaths and a
            # healed node can never come back talking to be fenced.
            p.heal()
            row["healed_s"] = round(now_s, 3) if now_s is not None \
                else "on_stop"
        except Exception as e:
            # Healing a partition on a node that died mid-partition
            # fails by construction; the row says so.
            swallow.noted("chaos.heal", e)
            row["healed_s"] = f"heal failed: {type(e).__name__}"
        finally:
            try:
                p.close()
            except Exception as e:
                swallow.noted("chaos.heal", e)
