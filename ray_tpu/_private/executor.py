"""Task execution on a worker thread.

Parity: reference executor path ``CoreWorker::ExecuteTask``
(core_worker.cc:2255) -> Cython ``task_execution_handler``
(_raylet.pyx:778) -> ``execute_task`` (:481): deserialize/pin args, load the
function from the GCS function store, run it, store returns (small ->
owner's in-process store "inline reply"; large -> node plasma-equivalent +
location registered with the directory).
"""

from __future__ import annotations

import logging
import threading
import time

from ray_tpu import exceptions
from ray_tpu._private import worker_context
from ray_tpu._private.config import get_config
from ray_tpu._private.debug.lock_order import diag_lock
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import DeviceObject, entry_value
from ray_tpu._private.serialization import deserialize, serialize
from ray_tpu._private.task_spec import TaskSpec, TaskType

logger = logging.getLogger(__name__)


def resolve_args(spec: TaskSpec, node, core_worker):
    """Materialize task arguments (GetAndPinArgsForExecutor parity)."""
    out = []
    for arg in spec.args:
        if arg.is_inline:
            out.append(deserialize(arg.value))
        else:
            value = core_worker.get_for_executor(arg.object_id, node)
            out.append(value)
    return out


def store_returns(spec: TaskSpec, values, node, core_worker):
    """Store return values; returns list of (object_id, size)."""
    cfg = get_config()
    num = spec.num_returns
    if num == 1:
        values = [values]
    elif num == 0:
        return []
    else:
        values = list(values)
        if len(values) != num:
            raise ValueError(
                f"Task {spec.function_name} returned {len(values)} values, "
                f"expected num_returns={num}")
    results = []
    for i, value in enumerate(values):
        oid = ObjectID.from_index(spec.task_id, i + 1)
        results.append((oid, core_worker.put_return_value(oid, value, node)))
    return results


def execute_task(spec: TaskSpec, node, core_worker, actor_instance=None):
    """Run one task on the current thread; returns (ok, error).

    On success return values are already stored.  On failure the caller
    (TaskManager) decides between retry and storing error objects.
    """
    from ray_tpu.gcs import task_events
    from ray_tpu.util import tracing
    ctx = worker_context.ExecutionContext(
        task_spec=spec, node=node,
        worker=worker_context.get_context().worker,
        actor_instance=actor_instance)
    prev = worker_context.get_context()
    worker_context.set_context(ctx)
    wid = getattr(ctx.worker, "worker_id", None)
    task_events.emit(node.cluster, spec.task_id, task_events.RUNNING,
                     node_id=node.node_id.hex(),
                     worker_id=wid.hex() if wid is not None else "")
    t0 = time.monotonic()
    trace_ctx = getattr(spec, "trace_ctx", None)
    try:
        # ``force=bool(trace_ctx)``: a traced submit makes the execute
        # span recorded even in a worker process that never enabled
        # capture itself — the events ride the reply back to the driver
        # (ProfileEvent batching parity, profiling.h:64).
        with tracing.span(f"execute:{spec.function_name}",
                          category="execute", parent=trace_ctx,
                          force=bool(trace_ctx),
                          task_id=spec.task_id.hex()):
            args, kwargs = _split_args(resolve_args(spec, node, core_worker))
            with _applied_runtime_env(spec, node):
                if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                    fn = core_worker.function_manager.load(spec.function_id)
                    instance = fn(*args, **kwargs)
                    return True, instance
                elif spec.task_type == TaskType.ACTOR_TASK:
                    method = getattr(actor_instance,
                                     spec.actor_method_name)
                    result = method(*args, **kwargs)
                else:
                    fn = core_worker.function_manager.load(spec.function_id)
                    result = fn(*args, **kwargs)
            store_returns(spec, result, node, core_worker)
        return True, None
    except Exception as e:  # noqa: BLE001 — user exceptions cross the boundary
        return False, exceptions.TaskError(
            e, task_desc=f"{spec.function_name}[{spec.task_id.hex()[:8]}]")
    finally:
        worker_context.set_context(prev)
        core_worker.record_task_metric(spec, time.monotonic() - t0)


_env_ctx_cache: dict = {}
_env_ctx_lock = diag_lock("executor._env_ctx_lock")


def _applied_runtime_env(spec: TaskSpec, node):
    """Thread-mode runtime-env application around the task body (process
    workers get the env injected at spawn instead).  Materialized
    contexts are cached per env hash (uri_cache.py parity)."""
    import contextlib

    from ray_tpu._private import runtime_env as runtime_env_mod
    renv = spec.runtime_env
    if not renv:
        return contextlib.nullcontext()
    h = renv.get("_hash") or runtime_env_mod.env_hash(renv)
    with _env_ctx_lock:
        env_ctx = _env_ctx_cache.get(h)
    if env_ctx is None:
        env_ctx = runtime_env_mod.materialize(renv, node.cluster.gcs.kv)
        with _env_ctx_lock:
            _env_ctx_cache[h] = env_ctx
    return runtime_env_mod.applied(env_ctx)


class _KwMark:
    """Marker separating positional args from flattened kwargs."""

    def __reduce__(self):
        return (_KwMark, ())


def pack_args(args, kwargs):
    """Flatten (args, kwargs) into one positional list for the spec.

    Each kwarg value stays a *top-level* arg so ObjectRefs passed by
    keyword are resolved to values on the executor side, matching the
    reference's signature flattening (python/ray/_private/signature.py).
    """
    packed = list(args)
    if kwargs:
        packed.append(_KwMark())
        packed.append(tuple(kwargs.keys()))
        packed.extend(kwargs.values())
    return packed


def _split_args(flat):
    for i, v in enumerate(flat):
        if isinstance(v, _KwMark):
            keys = flat[i + 1]
            values = flat[i + 2:]
            return list(flat[:i]), dict(zip(keys, values))
    return list(flat), {}
