"""Object plane: location directory + node-to-node transfer.

Parity: reference ``src/ray/object_manager/`` — the
``OwnershipBasedObjectDirectory`` (owners are the source of truth for object
locations, ownership_based_object_directory.cc), ``PullManager``
(admission-controlled pulls with retry, pull_manager.cc) and ``PushManager``
(chunked pushes, push_manager.cc).  Transfers here copy the serialized bytes
chunk-by-chunk between node stores (object_manager_chunk_size), preserving
the chunked-flow structure the gRPC path would have.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ray_tpu import exceptions
from ray_tpu._private import fault_injection
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.object_store import NodeObjectStore, _NativeHandle
from ray_tpu._private.debug import diag_lock


def fetch_object_into(client, object_id: ObjectID, local_store,
                      pipeline: int = 8, on_chunk=None,
                      timeout: float = 300.0):
    """One complete streamed pull over ``client``: negotiate the chunk
    session (inline reply / busy-backoff / windowed pipeline) and
    assemble the object DIRECTLY into a reserved block of
    ``local_store`` via ``create_transfer_writer`` — the shared receive
    half of the zero-copy data plane, used by spoke-to-peer, spoke-to-
    head and head-to-spoke pulls alike.  Returns the flat byte count on
    success, None on failure/absence."""
    from ray_tpu._private.serialization import SerializedObject
    from ray_tpu.rpc.chunked import fetch_session_into
    deadline = time.monotonic() + timeout
    backoff = 0.02
    while True:
        meta = client.call("fetch_meta",
                           {"object_id": object_id.binary()},
                           timeout=min(60.0, timeout))
        if meta is None:
            return None              # source has no copy
        if "inline" in meta:
            blob = meta["inline"]
            local_store.put(object_id, SerializedObject.from_bytes(blob),
                            pin=False)
            if on_chunk is not None:
                on_chunk(len(blob), 0)
            return len(blob)
        if meta.get("busy"):
            # Sender admission control: back off and retry.
            if time.monotonic() >= deadline:
                return None
            time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)
            continue
        try:
            # May QUEUE behind the receiver store's create-request
            # backpressure (space freed by seals/evictions/spills); a
            # grace-deadline miss is a failed pull, not a crash.  A
            # None writer means a concurrent pull of the same object
            # already delivered it (single-writer dedupe): report 0
            # bytes — THIS pull transferred nothing, and counting the
            # object size would double-book pulled_bytes /
            # cross_node_fetch_bytes against the racing transfer.
            writer = local_store.create_transfer_writer(object_id,
                                                        meta["size"])
            if writer is None:
                return 0
        except exceptions.ObjectStoreFullError as err:
            if getattr(err, "infeasible", False):
                # The object exceeds this store's TOTAL capacity: no
                # amount of spilling/retrying can ever admit it.
                # Surface the actionable error instead of burning the
                # pull deadline on futile retries.
                raise
            return None
        ok = False
        try:
            ok = fetch_session_into(
                client, meta, writer.write,
                timeout=max(0.0, deadline - time.monotonic()),
                pipeline=pipeline, on_chunk=on_chunk)
        finally:
            if ok:
                writer.seal()
            else:
                writer.abort()
        return meta["size"] if ok else None


class ObjectDirectory:
    """Object location directory (ownership-based in the reference; the
    owner table lives with the driver core worker here and this directory
    is its queryable index)."""

    def __init__(self):
        self._lock = diag_lock("ObjectDirectory._lock")
        self._locations: Dict[ObjectID, Set[NodeID]] = {}
        # Serialized byte size per object (recorded alongside the first
        # location): the arg-locality cost term weighs candidate nodes
        # by the argument bytes they already hold, so sizes must flow
        # through the directory, not just locations.
        self._sizes: Dict[ObjectID, int] = {}
        self._subscribers: Dict[ObjectID, List[Callable]] = {}

    def add_location(self, object_id: ObjectID, node_id: NodeID,
                     size: Optional[int] = None):
        with self._lock:
            self._locations.setdefault(object_id, set()).add(node_id)
            if size:
                self._sizes[object_id] = int(size)
            subs = self._subscribers.pop(object_id, [])
        for cb in subs:
            cb(node_id)

    def size_hint(self, object_id: ObjectID) -> int:
        """Serialized bytes of the object, or 0 when unknown (small
        inlined objects never register — they cost nothing to move)."""
        with self._lock:
            return self._sizes.get(object_id, 0)

    def remove_location(self, object_id: ObjectID, node_id: NodeID):
        with self._lock:
            locs = self._locations.get(object_id)
            if locs:
                locs.discard(node_id)
                if not locs:
                    del self._locations[object_id]
                    self._sizes.pop(object_id, None)

    def remove_object(self, object_id: ObjectID):
        with self._lock:
            self._locations.pop(object_id, None)
            self._sizes.pop(object_id, None)
            # A freed object can never gain a location; drop its waiters
            # (wait() wakeup hooks would otherwise accumulate forever).
            self._subscribers.pop(object_id, None)

    def get_locations(self, object_id: ObjectID) -> Set[NodeID]:
        with self._lock:
            return set(self._locations.get(object_id, ()))

    def subscribe_location(self, object_id: ObjectID, cb: Callable):
        """Callback fired when the first location appears."""
        with self._lock:
            locs = self._locations.get(object_id)
            if locs:
                node = next(iter(locs))
            else:
                self._subscribers.setdefault(object_id, []).append(cb)
                return
        cb(node)

    def unsubscribe_location(self, object_id: ObjectID, cb: Callable):
        """Deregister a pending location subscription (no-op if it
        already fired or was never registered)."""
        with self._lock:
            subs = self._subscribers.get(object_id)
            if subs is None:
                return
            try:
                subs.remove(cb)
            except ValueError:
                return
            if not subs:
                del self._subscribers[object_id]

    def on_node_death(self, node_id: NodeID) -> List[ObjectID]:
        """Remove all locations on a dead node; returns objects that lost
        their last copy (candidates for lineage reconstruction)."""
        lost = []
        with self._lock:
            for oid, locs in list(self._locations.items()):
                if node_id in locs:
                    locs.discard(node_id)
                    if not locs:
                        del self._locations[oid]
                        self._sizes.pop(oid, None)
                        lost.append(oid)
        return lost


class NodeObjectManager:
    """Per-node transfer manager (PullManager/PushManager parity)."""

    def __init__(self, raylet, directory: ObjectDirectory):
        self._raylet = raylet
        self._directory = directory
        self._lock = diag_lock("NodeObjectManager._lock")
        self._inflight_pulls: Dict[ObjectID, List[Callable]] = {}
        # Transfers run on their own IO pool — a multi-GiB pull on the
        # raylet's event loop would stall its heartbeats and scheduling
        # ticks (the reference's pull manager runs on dedicated io
        # contexts for the same reason).  Daemon workers + stop():
        # in-flight pulls must not block process exit.
        from ray_tpu._private.daemon_pool import DaemonPool
        self._pull_pool = DaemonPool(
            4, name=f"ray_tpu::pull::{raylet.node_id.hex()[:6]}")
        self.stats = {"pulled_objects": 0, "pulled_bytes": 0,
                      # Bytes fetched from OTHER nodes to satisfy local
                      # work — the placement-quality metric the
                      # arg-locality cost term is measured against
                      # (locality-aware placement should shrink it).
                      "cross_node_fetch_bytes": 0,
                      "chunks_transferred": 0, "failed_pulls": 0,
                      "transfer_gbps_last": 0.0,
                      "inflight_window_peak": 0}
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
        nid = raylet.node_id.hex()[:12]

        def _collect(om):
            labels = {"node": nid}
            for k, v in om.stats.items():
                record_internal(f"ray_tpu.object_manager.{k}", v, **labels)
        get_metrics_registry().register_collector(self, _collect)

    # ---- queries --------------------------------------------------------
    def is_local_or_inline(self, object_id: ObjectID) -> bool:
        if self._raylet.object_store.contains(object_id):
            return True
        # Small objects live in the owner's in-process memory store and are
        # readable from any node in-process ("inlined in PushTask").  An
        # InPlasmaMarker does NOT count: the real bytes are on some node
        # and must be pulled.
        core = self._raylet.core_worker
        if core is None:
            return False
        from ray_tpu._private.object_store import InPlasmaMarker
        entry = core.memory_store.get_entry(object_id)
        return entry is not None and entry.sealed and \
            not isinstance(entry.data, InPlasmaMarker)

    # ---- pull path ------------------------------------------------------
    def pull_async(self, object_id: ObjectID, cb: Callable[[bool], None]):
        if self.is_local_or_inline(object_id):
            cb(True)
            return
        with self._lock:
            waiters = self._inflight_pulls.get(object_id)
            if waiters is not None:
                waiters.append(cb)
                return
            self._inflight_pulls[object_id] = [cb]

        def finish(ok: bool):
            with self._lock:
                waiters = self._inflight_pulls.pop(object_id, None)
            if waiters is None:
                return  # another path already finished this pull
            for w in waiters:
                w(ok)

        def attempt(node_id):
            if self.is_local_or_inline(object_id):
                finish(True)
                return
            # finish() MUST run no matter how the transfer dies: an
            # escaped exception would be swallowed by the pull pool,
            # leaving every waiter (and all future pulls of this id,
            # parked on the orphaned inflight entry) hung forever.
            try:
                ok = self._fetch_from(object_id, node_id)
            except Exception:
                ok = False
            finish(ok)

        locations = self._directory.get_locations(object_id)
        if locations:
            self._pull_pool.submit(attempt, next(iter(locations)))
            return
        # Freed object: nothing will ever produce it again — fail fast
        # instead of subscribing forever (the caller may try lineage
        # reconstruction).
        core = self._raylet.core_worker
        if core is not None and \
                not core.reference_counter.has_reference(object_id) and \
                not core.task_manager.is_pending(object_id.task_id()):
            finish(False)
            return
        # No location yet: the object may still be computing.  Watch both
        # signals — a directory location (big objects land in a node store)
        # and the owner's memory store (small returns are "inlined" there,
        # never registered with the directory) — first one wins.  Mirrors
        # the pull manager's retry loop + memory-store GetAsync.
        self._directory.subscribe_location(
            object_id,
            lambda node_id: self._pull_pool.submit(attempt, node_id))
        core = self._raylet.core_worker
        if core is not None:
            core.memory_store.get_async(
                object_id, lambda entry: finish(True))

    def stop(self):
        self._pull_pool.stop()

    def _retry_other_location(self, object_id: ObjectID,
                              tried: set) -> bool:
        """A source was unusable (dead, stale, failed copy): try the
        remaining known locations before declaring the pull failed —
        one bad directory row must not fail a pull the other rows could
        have served."""
        for other in self._directory.get_locations(object_id):
            if other not in tried:
                return self._fetch_from(object_id, other, tried)
        return False

    def _fetch_from(self, object_id: ObjectID, node_id: NodeID,
                    _tried: Optional[set] = None) -> bool:
        """Streamed transfer of the serialized object from a remote node
        store into the local store (ObjectBufferPool chunk assembly
        parity) — single-copy end to end:

        * cross-process peers: a WINDOWED pipeline of in-flight chunk
          requests (rpc/chunked.py) assembles each chunk directly into a
          reserved local shm-segment block — no intermediate
          ``bytearray``, no whole-blob RPC;
        * in-process source stores (simulated multi-node): the source's
          segment view is copied chunk-by-chunk straight into the local
          reservation under a source-side pin.

        Per-transfer throughput and the in-flight window peak are
        exported through the metrics agent."""
        tried = set() if _tried is None else _tried
        tried.add(node_id)
        local_id = self._raylet.node_id
        if node_id == local_id:
            if self._raylet.object_store.contains(object_id):
                # The object landed locally since the caller's check
                # (concurrent put/restore): the pull's goal is met.
                return True
            # A stale SELF-location (the local copy was dropped after
            # the directory row was written — e.g. a vanished-entry
            # heal): "pulling from ourselves" can never succeed.  Drop
            # the lying row and pull from a genuine remote copy.
            self._directory.remove_location(object_id, local_id)
            return self._retry_other_location(object_id, tried)
        source = self._raylet.cluster.gcs.raylet(node_id)
        if source is None:
            # Source died; try another location or give up.
            return self._retry_other_location(object_id, tried)
        from ray_tpu.util import tracing
        transfer_span = tracing.span(
            "object.transfer", category="transfer",
            node=self._raylet.node_id.hex()[:12],
            source=node_id.hex()[:12])
        transfer_span.__enter__()
        t0 = time.monotonic()
        reader = source.object_store
        window_peak = [0]

        def on_chunk(nbytes: int, inflight: int):
            # Chaos point: per-chunk delay (slow network) or error
            # (truncated transfer -> abort + retry path).
            fault_injection.hook("transfer.chunk")
            self.stats["chunks_transferred"] += 1
            if inflight > window_peak[0]:
                window_peak[0] = inflight

        try:
            if hasattr(reader, "fetch_into"):
                # Cross-process peer: pipelined chunk stream into the
                # local segment (PullManager admission + ack flow).
                nbytes = reader.fetch_into(
                    object_id, self._raylet.object_store,
                    pipeline=get_config().object_transfer_pipeline_depth,
                    on_chunk=on_chunk)
            elif isinstance(reader, NodeObjectStore):
                nbytes = self._copy_local(object_id, reader, on_chunk)
            else:
                nbytes = self._copy_via_serialized(object_id, reader,
                                                   on_chunk)
        except BaseException:
            transfer_span.meta["ok"] = False
            transfer_span.__exit__(None, None, None)
            raise
        if nbytes is None:
            self.stats["failed_pulls"] += 1
            transfer_span.meta["ok"] = False
            transfer_span.__exit__(None, None, None)
            return self._retry_other_location(object_id, tried)
        self.stats["pulled_objects"] += 1
        # The object is local either way — the location row is true
        # even when a racing transfer moved the bytes.
        self._directory.add_location(object_id, self._raylet.node_id,
                                     size=nbytes or None)
        if nbytes:
            # nbytes == 0 = the single-writer dedupe adopted a racing
            # transfer's copy: THIS pull moved no bytes — byte counters
            # and the transfer rate must not be booked for it.
            self.stats["pulled_bytes"] += nbytes
            self.stats["cross_node_fetch_bytes"] += nbytes
            elapsed = max(time.monotonic() - t0, 1e-9)
            self.stats["transfer_gbps_last"] = round(
                nbytes / elapsed / 1e9, 3)
            from ray_tpu._private.metrics_agent import (observe_internal,
                                                        record_internal)
            record_internal("ray_tpu.object_manager.transfer_gbps",
                            nbytes / elapsed / 1e9,
                            node=self._raylet.node_id.hex()[:12])
            observe_internal("ray_tpu.object_manager.transfer_seconds",
                             elapsed)
        self.stats["inflight_window_peak"] = max(
            self.stats["inflight_window_peak"], window_peak[0])
        transfer_span.meta["bytes"] = nbytes
        transfer_span.__exit__(None, None, None)
        return True

    def _copy_local(self, object_id: ObjectID, src: "NodeObjectStore",
                    on_chunk) -> Optional[int]:
        """In-process store-to-store transfer: chunked copy from the
        source's segment view directly into a local reservation.  The
        source block is pinned for the duration so eviction cannot
        recycle it mid-read.  A SPILLED source is served straight from
        its spill-file mmap — the transfer never forces the sender to
        restore the bytes into its store budget."""
        spilled = src.open_spilled_view(object_id)
        if spilled is not None:
            view, release = spilled
            try:
                return self._chunk_copy_into_local(object_id, view,
                                                   on_chunk)
            finally:
                release()
        entry = src.get(object_id)
        if entry is None:
            return None
        data = entry.data
        if isinstance(data, _NativeHandle) and src._native is not None:
            key = data.key
            # Pin failure = the block was spilled/freed in the window;
            # fall through to the serialized leg, whose get() restores
            # spilled bytes — the object may still be recoverable.
            if src._native.pin(key):
                try:
                    view = data.read()
                    if view is not None:
                        return self._chunk_copy_into_local(
                            object_id, view, on_chunk)
                finally:
                    src._native.unpin(key)
        return self._copy_via_serialized(object_id, src, on_chunk)

    def _chunk_copy_into_local(self, object_id: ObjectID, view,
                               on_chunk) -> int:
        """Chunk-copy a flat source view (pinned segment block or
        spill-file mmap) into a reserved local store block."""
        nbytes = view.nbytes
        store = self._raylet.object_store
        writer = store.create_transfer_writer(object_id, nbytes)
        if writer is None:
            return 0             # a concurrent pull already delivered it
        try:
            chunk = get_config().object_manager_chunk_size
            for off in range(0, nbytes, chunk):
                writer.write(off, view[off:off + chunk])
                on_chunk(min(chunk, nbytes - off), 0)
            writer.seal()
        except BaseException:
            writer.abort()
            raise
        return nbytes

    def _copy_via_serialized(self, object_id: ObjectID, reader,
                             on_chunk) -> Optional[int]:
        """Generic leg (python-held / device / proxy sources): the
        source hands back a SerializedObject whose buffers are
        heap-backed (kept alive by the views), and the local put moves
        them straight into the local segment — still one data copy."""
        serialized = reader.get_serialized(object_id)
        if serialized is None:
            return None
        nbytes = serialized.flat_nbytes
        self._raylet.object_store.put(object_id, serialized, pin=False)
        chunk = get_config().object_manager_chunk_size
        for off in range(0, nbytes, chunk):
            on_chunk(min(chunk, nbytes - off), 0)
        return nbytes
