"""Object plane: location directory + node-to-node transfer.

Parity: reference ``src/ray/object_manager/`` — the
``OwnershipBasedObjectDirectory`` (owners are the source of truth for object
locations, ownership_based_object_directory.cc), ``PullManager``
(admission-controlled pulls with retry, pull_manager.cc) and ``PushManager``
(chunked pushes, push_manager.cc).  Transfers here copy the serialized bytes
chunk-by-chunk between node stores (object_manager_chunk_size), preserving
the chunked-flow structure the gRPC path would have.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ray_tpu import exceptions
from ray_tpu._private import fault_injection
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.object_store import NodeObjectStore, _NativeHandle
from ray_tpu._private.debug import diag_lock, flight_recorder


def fetch_object_into(client, object_id: ObjectID, local_store,
                      pipeline: int = 8, on_chunk=None,
                      timeout: float = 300.0,
                      busy_patience_s: Optional[float] = None):
    """One complete streamed pull over ``client``: negotiate the chunk
    session (inline reply / busy-backoff / windowed pipeline) and
    assemble the object DIRECTLY into a reserved block of
    ``local_store`` via ``create_transfer_writer`` — the shared receive
    half of the zero-copy data plane, used by spoke-to-peer, spoke-to-
    head and head-to-spoke pulls alike.  Returns the flat byte count on
    success, None on failure/absence.

    ``busy_patience_s`` bounds how long ``busy`` replies are retried
    against THIS source before giving up (the caller re-selects a
    less-loaded location); None = retry until the pull deadline (the
    single-source behavior — a storm degrades to queuing)."""
    from ray_tpu._private.serialization import SerializedObject
    from ray_tpu.rpc.chunked import fetch_session_into
    deadline = time.monotonic() + timeout
    busy_deadline = None if busy_patience_s is None else \
        time.monotonic() + busy_patience_s
    backoff = 0.02
    while True:
        meta = client.call("fetch_meta",
                           {"object_id": object_id.binary()},
                           timeout=min(60.0, timeout))
        if meta is None:
            return None              # source has no copy
        if "inline" in meta:
            blob = meta["inline"]
            local_store.put(object_id, SerializedObject.from_bytes(blob),
                            pin=False)
            if on_chunk is not None:
                on_chunk(len(blob), 0)
            return len(blob)
        if meta.get("busy"):
            # Sender admission control: back off and retry (bounded by
            # busy_patience_s when the caller has other sources).
            now = time.monotonic()
            if now >= deadline or \
                    (busy_deadline is not None and now >= busy_deadline):
                return None
            time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)
            continue
        try:
            # May QUEUE behind the receiver store's create-request
            # backpressure (space freed by seals/evictions/spills); a
            # grace-deadline miss is a failed pull, not a crash.  A
            # None writer means a concurrent pull of the same object
            # already delivered it (single-writer dedupe): report 0
            # bytes — THIS pull transferred nothing, and counting the
            # object size would double-book pulled_bytes /
            # cross_node_fetch_bytes against the racing transfer.
            writer = local_store.create_transfer_writer(object_id,
                                                        meta["size"])
            if writer is None:
                return 0
        except exceptions.ObjectStoreFullError as err:
            if getattr(err, "infeasible", False):
                # The object exceeds this store's TOTAL capacity: no
                # amount of spilling/retrying can ever admit it.
                # Surface the actionable error instead of burning the
                # pull deadline on futile retries.
                raise
            return None
        ok = False
        try:
            ok = fetch_session_into(
                client, meta, writer.write,
                timeout=max(0.0, deadline - time.monotonic()),
                pipeline=pipeline, on_chunk=on_chunk)
        finally:
            if ok:
                writer.seal()
            else:
                writer.abort()
        return meta["size"] if ok else None


class ObjectDirectory:
    """Object location directory (ownership-based in the reference; the
    owner table lives with the driver core worker here and this directory
    is its queryable index)."""

    def __init__(self):
        self._lock = diag_lock("ObjectDirectory._lock")
        self._locations: Dict[ObjectID, Set[NodeID]] = {}
        # Serialized byte size per object (recorded alongside the first
        # location): the arg-locality cost term weighs candidate nodes
        # by the argument bytes they already hold, so sizes must flow
        # through the directory, not just locations.
        self._sizes: Dict[ObjectID, int] = {}
        self._subscribers: Dict[ObjectID, List[Callable]] = {}
        # PARTIAL rows (chunk relay): node -> registration seq for
        # objects a node is mid-pull of and can relay the assembled
        # prefix of.  Never surfaced through get_locations — only
        # get_candidates — so every legacy caller keeps full-copy
        # semantics.
        self._partials: Dict[ObjectID, Dict[NodeID, int]] = {}
        self._partial_seq: Dict[ObjectID, int] = {}

    def add_partial_location(self, object_id: ObjectID,
                             node_id: NodeID) -> int:
        """Register a PARTIAL location row: ``node_id`` is mid-pull of
        the object and can relay its assembled prefix downstream.
        Returns the row's per-object sequence number — a puller may
        relay only from rows with a LOWER seq than its own, so relay
        edges point strictly backward in registration order and chains
        are cycle-free by construction."""
        with self._lock:
            seq = self._partial_seq.get(object_id, 0) + 1
            self._partial_seq[object_id] = seq
            self._partials.setdefault(object_id, {})[node_id] = seq
        return seq

    def remove_partial_location(self, object_id: ObjectID,
                                node_id: NodeID):
        """Drop a partial row (transfer sealed into a full row, or
        aborted).  The per-object seq counter is deliberately kept
        while the object lives: a fresh registration must never reuse
        a seq an in-flight puller already compares against."""
        with self._lock:
            rows = self._partials.get(object_id)
            if rows:
                rows.pop(node_id, None)
                if not rows:
                    del self._partials[object_id]

    def get_candidates(self, object_id: ObjectID) -> List[dict]:
        """Every source a pull may stream from: full rows
        (``partial=False, seq=0``) plus partial relay rows with their
        registration seq.  Rows carry the object's size hint so
        pullers can skip relay bookkeeping for sub-chunk objects."""
        with self._lock:
            size = self._sizes.get(object_id, 0)
            full = self._locations.get(object_id, set())
            out = [{"node_id": n, "partial": False, "seq": 0,
                    "size": size}
                   for n in full]
            for n, seq in (self._partials.get(object_id) or {}).items():
                if n not in full:
                    out.append({"node_id": n, "partial": True,
                                "seq": seq, "size": size})
        return out

    def add_location(self, object_id: ObjectID, node_id: NodeID,
                     size: Optional[int] = None):
        with self._lock:
            self._locations.setdefault(object_id, set()).add(node_id)
            if size:
                self._sizes[object_id] = int(size)
            subs = self._subscribers.pop(object_id, [])
        for cb in subs:
            cb(node_id)

    def size_hint(self, object_id: ObjectID) -> int:
        """Serialized bytes of the object, or 0 when unknown (small
        inlined objects never register — they cost nothing to move)."""
        with self._lock:
            return self._sizes.get(object_id, 0)

    def remove_location(self, object_id: ObjectID, node_id: NodeID):
        with self._lock:
            locs = self._locations.get(object_id)
            if locs:
                locs.discard(node_id)
                if not locs:
                    del self._locations[object_id]
                    self._sizes.pop(object_id, None)

    def remove_object(self, object_id: ObjectID):
        with self._lock:
            self._locations.pop(object_id, None)
            self._sizes.pop(object_id, None)
            self._partials.pop(object_id, None)
            self._partial_seq.pop(object_id, None)
            # A freed object can never gain a location; drop its waiters
            # (wait() wakeup hooks would otherwise accumulate forever).
            self._subscribers.pop(object_id, None)

    def get_locations(self, object_id: ObjectID) -> Set[NodeID]:
        with self._lock:
            return set(self._locations.get(object_id, ()))

    def subscribe_location(self, object_id: ObjectID, cb: Callable):
        """Callback fired when the first location appears."""
        with self._lock:
            locs = self._locations.get(object_id)
            if locs:
                node = next(iter(locs))
            else:
                self._subscribers.setdefault(object_id, []).append(cb)
                return
        cb(node)

    def unsubscribe_location(self, object_id: ObjectID, cb: Callable):
        """Deregister a pending location subscription (no-op if it
        already fired or was never registered)."""
        with self._lock:
            subs = self._subscribers.get(object_id)
            if subs is None:
                return
            try:
                subs.remove(cb)
            except ValueError:
                return
            if not subs:
                del self._subscribers[object_id]

    def on_node_death(self, node_id: NodeID) -> List[ObjectID]:
        """Remove all locations on a dead node; returns objects that lost
        their last copy (candidates for lineage reconstruction)."""
        lost = []
        with self._lock:
            for oid, locs in list(self._locations.items()):
                if node_id in locs:
                    locs.discard(node_id)
                    if not locs:
                        del self._locations[oid]
                        self._sizes.pop(oid, None)
                        lost.append(oid)
            # A dead node can relay nothing: prune its partial rows so
            # downstream pullers stop being routed to it.
            for oid, rows in list(self._partials.items()):
                if rows.pop(node_id, None) is not None and not rows:
                    del self._partials[oid]
        return lost


class NodeObjectManager:
    """Per-node transfer manager (PullManager/PushManager parity)."""

    def __init__(self, raylet, directory: ObjectDirectory):
        self._raylet = raylet
        self._directory = directory
        self._lock = diag_lock("NodeObjectManager._lock")
        self._inflight_pulls: Dict[ObjectID, List[Callable]] = {}
        # Transfers run on their own IO pool — a multi-GiB pull on the
        # raylet's event loop would stall its heartbeats and scheduling
        # ticks (the reference's pull manager runs on dedicated io
        # contexts for the same reason).  Daemon workers + stop():
        # in-flight pulls must not block process exit.
        from ray_tpu._private.daemon_pool import DaemonPool
        self._pull_pool = DaemonPool(
            4, name=f"ray_tpu::pull::{raylet.node_id.hex()[:6]}")
        self.stats = {"pulled_objects": 0, "pulled_bytes": 0,
                      # Bytes fetched from OTHER nodes to satisfy local
                      # work — the placement-quality metric the
                      # arg-locality cost term is measured against
                      # (locality-aware placement should shrink it).
                      "cross_node_fetch_bytes": 0,
                      "chunks_transferred": 0, "failed_pulls": 0,
                      "transfer_gbps_last": 0.0,
                      "inflight_window_peak": 0,
                      # Collective-transfer counters: pulls streamed
                      # from a relay (partial) source, and admission
                      # waits abandoned for a less-loaded source.
                      "relay_pulls": 0, "load_reselects": 0}
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
        nid = raylet.node_id.hex()[:12]

        def _collect(om):
            labels = {"node": nid}
            for k, v in om.stats.items():
                record_internal(f"ray_tpu.object_manager.{k}", v, **labels)
        get_metrics_registry().register_collector(self, _collect)

    # ---- queries --------------------------------------------------------
    def is_local_or_inline(self, object_id: ObjectID) -> bool:
        if self._raylet.object_store.contains(object_id):
            return True
        # Small objects live in the owner's in-process memory store and are
        # readable from any node in-process ("inlined in PushTask").  An
        # InPlasmaMarker does NOT count: the real bytes are on some node
        # and must be pulled.
        core = self._raylet.core_worker
        if core is None:
            return False
        from ray_tpu._private.object_store import InPlasmaMarker
        entry = core.memory_store.get_entry(object_id)
        return entry is not None and entry.sealed and \
            not isinstance(entry.data, InPlasmaMarker)

    # ---- pull path ------------------------------------------------------
    def pull_async(self, object_id: ObjectID, cb: Callable[[bool], None]):
        if self.is_local_or_inline(object_id):
            cb(True)
            return
        with self._lock:
            waiters = self._inflight_pulls.get(object_id)
            if waiters is not None:
                waiters.append(cb)
                return
            self._inflight_pulls[object_id] = [cb]

        def finish(ok: bool):
            with self._lock:
                waiters = self._inflight_pulls.pop(object_id, None)
            if waiters is None:
                return  # another path already finished this pull
            for w in waiters:
                w(ok)

        def attempt(_hint=None):
            if self.is_local_or_inline(object_id):
                finish(True)
                return
            # finish() MUST run no matter how the transfer dies: an
            # escaped exception would be swallowed by the pull pool,
            # leaving every waiter (and all future pulls of this id,
            # parked on the orphaned inflight entry) hung forever.
            try:
                ok = self._pull_once(object_id)
            except Exception:
                ok = False
            finish(ok)

        if self._candidate_rows(object_id):
            self._pull_pool.submit(attempt)
            return
        # Freed object: nothing will ever produce it again — fail fast
        # instead of subscribing forever (the caller may try lineage
        # reconstruction).
        core = self._raylet.core_worker
        if core is not None and \
                not core.reference_counter.has_reference(object_id) and \
                not core.task_manager.is_pending(object_id.task_id()):
            finish(False)
            return
        # No location yet: the object may still be computing.  Watch both
        # signals — a directory location (big objects land in a node store)
        # and the owner's memory store (small returns are "inlined" there,
        # never registered with the directory) — first one wins.  Mirrors
        # the pull manager's retry loop + memory-store GetAsync.
        self._directory.subscribe_location(
            object_id,
            lambda node_id: self._pull_pool.submit(attempt, node_id))
        core = self._raylet.core_worker
        if core is not None:
            core.memory_store.get_async(
                object_id, lambda entry: finish(True))

    def stop(self):
        self._pull_pool.stop()

    # ---- source selection (load-aware, relay-capable) -------------------
    #: Bounded pull rounds: each consumes one candidate source (or one
    #: tried-set reset); a pull that cannot land in this many attempts
    #: reports failure to its waiters (lineage recovery decides next).
    MAX_SOURCE_ROUNDS = 16
    #: Sentinel: the source was merely BUSY and a freer one exists —
    #: re-run selection without marking the source as failed.
    _RESELECT = object()

    def _candidate_rows(self, object_id: ObjectID) -> List[dict]:
        d = self._directory
        if hasattr(d, "get_candidates"):
            return d.get_candidates(object_id)
        return [{"node_id": n, "partial": False, "seq": 0}
                for n in d.get_locations(object_id)]

    def _source_load(self, row: dict):
        """Live outbound-load score for a candidate source: the
        in-process ledger when the source shares this process (exact),
        else the load hint the directory reply carried (head-reported,
        at most one resource-poll stale), else zero."""
        raylet = self._raylet.cluster.gcs.raylet(row["node_id"])
        store = getattr(raylet, "object_store", None)
        ledger = getattr(store, "transfer_ledger", None)
        if ledger is not None:
            return ledger.load_score()
        report = getattr(raylet, "_last_report", None)
        hint = row.get("load") or (report or {}).get("transfer_load")
        if hint:
            return (int(hint.get("active", 0)) + int(hint.get("queued",
                                                              0)),
                    int(hint.get("inflight_bytes", 0)))
        return (0, 0)

    def _source_has_free_slot(self, row: dict) -> bool:
        raylet = self._raylet.cluster.gcs.raylet(row["node_id"])
        store = getattr(raylet, "object_store", None)
        ledger = getattr(store, "transfer_ledger", None)
        return ledger is not None and ledger.has_free_slot()

    def _select_source(self, object_id: ObjectID, tried: set,
                       my_seq: Optional[int],
                       require_free: bool = False,
                       rows: Optional[List[dict]] = None
                       ) -> Optional[dict]:
        """Pick the pull source (returns the candidate ROW, or None):
        weigh candidates by live outbound load (so concurrent pulls of
        one object spread across every node that holds a copy),
        admitting PARTIAL relay rows only with a lower registration seq
        than ours (cycle-free chains).  Ties break toward the
        HIGHEST-seq partial — the most recently started transfer, i.e.
        the deepest link of the chain, which is exactly where a new
        puller extends it.

        ``require_free`` restricts to sources with a free admission
        slot RIGHT NOW (the mid-queue re-selection probe; load mode
        only — the naive arm queues where it first landed)."""
        cfg = get_config()
        if require_free and \
                cfg.object_transfer_source_selection != "load":
            return None
        local_id = self._raylet.node_id
        allow_partial = cfg.object_transfer_relay_enabled and \
            my_seq is not None
        usable = []
        if rows is None:
            rows = self._candidate_rows(object_id)
        for row in rows:
            nid = row["node_id"]
            if nid is None or nid in tried:
                continue
            if nid == local_id:
                # A stale SELF-row (our copy was dropped after the row
                # was written, e.g. a vanished-entry heal): "pulling
                # from ourselves" can never succeed — drop the lying
                # row.  Our own partial registration is skipped
                # silently.
                if not row.get("partial") and \
                        not self._raylet.object_store.contains(object_id):
                    self._directory.remove_location(object_id, local_id)
                continue
            if row.get("partial"):
                if not allow_partial or row.get("seq", 0) >= my_seq:
                    continue
            usable.append(row)
        if not usable:
            return None
        if cfg.object_transfer_source_selection != "load":
            # Naive arm: first full directory row (pre-relay behavior).
            for row in usable:
                if not row.get("partial"):
                    return row
            return usable[0]
        if require_free:
            usable = [r for r in usable if self._source_has_free_slot(r)]
            if not usable:
                return None
        return min(usable,
                   key=lambda r: (self._source_load(r),
                                  -int(r.get("seq", 0))))

    def _relay_worthwhile(self, object_id: ObjectID,
                          rows: List[dict]) -> bool:
        """Partial-row registration gate: only multi-chunk objects can
        ever serve a relay (the store-side writer gate is the same), so
        sub-chunk pulls skip the directory round-trip entirely.  An
        unknown size (0) registers — functional-safe."""
        size = max((int(r.get("size") or 0) for r in rows), default=0)
        if size == 0:
            hint = getattr(self._directory, "size_hint", None)
            if hint is not None:
                size = hint(object_id)
        return size == 0 or size > get_config().object_manager_chunk_size

    def _pull_once(self, object_id: ObjectID) -> bool:
        """One complete pull: register our PARTIAL directory row first
        (downstream pullers can chain off our in-flight transfer), then
        stream from load-ranked sources until one delivers, healing or
        skipping bad rows along the way."""
        cfg = get_config()
        tried: set = set()
        my_seq = None
        local_id = self._raylet.node_id
        first_rows = self._candidate_rows(object_id)
        if cfg.object_transfer_relay_enabled and \
                hasattr(self._directory, "add_partial_location") and \
                self._relay_worthwhile(object_id, first_rows):
            try:
                my_seq = self._directory.add_partial_location(object_id,
                                                              local_id)
                flight_recorder.record(
                    "transfer.relay_register",
                    obj=object_id.hex()[:12], seq=my_seq,
                    node=local_id.hex()[:12])
            except Exception:
                my_seq = None       # relay off for this pull; still safe
        try:
            reset_used = False
            partial_failures = 0
            for _round in range(self.MAX_SOURCE_ROUNDS):
                if self.is_local_or_inline(object_id):
                    return True
                # After a couple of failed relay attempts, stop chasing
                # partial rows: in a simultaneous burst many rows exist
                # BEFORE any transfer writer does (each fails in
                # milliseconds), and a pull must degrade to queuing at
                # a full copy, never fail while the origin is healthy.
                # The LAST round enforces exactly that: full rows only,
                # no reselect, unbounded patience — reselect bounces
                # and busy sources can consume rounds, never the pull.
                final = _round >= self.MAX_SOURCE_ROUNDS - 1
                eff_seq = my_seq if partial_failures < 2 and not final \
                    else None
                rows = first_rows if first_rows is not None else \
                    self._candidate_rows(object_id)
                first_rows = None       # later rounds re-fetch (load)
                row = self._select_source(object_id, tried, eff_seq,
                                          rows=rows)
                if row is None:
                    if tried and not reset_used:
                        # Every candidate was consumed by transient
                        # failures (busy sources, a dead relay): one
                        # fresh pass over the directory before giving
                        # up — the rows may have changed under us.
                        tried.clear()
                        reset_used = True
                        continue
                    # Exhausted for good: one last PATIENT attempt on
                    # the best FULL row, if any — a merely-busy source
                    # must queue us to its grant, never fail the pull
                    # (the per-round busy-patience that consumed the
                    # rows above is bounded; this attempt is not).
                    tried.clear()
                    last = self._select_source(object_id, tried, None)
                    if last is None:
                        return False
                    return self._fetch_from(object_id,
                                            last["node_id"], tried,
                                            None,
                                            others_available=False)
                target = row["node_id"]
                # Flight recorder: the source-selection decision — which
                # candidate won, full copy or relay link, which round.
                flight_recorder.record(
                    "transfer.select", obj=object_id.hex()[:12],
                    source=target.hex()[:12],
                    partial=bool(row.get("partial")),
                    seq=int(row.get("seq") or 0), round=_round,
                    tried=len(tried))
                # Busy-patience only makes sense when somewhere else to
                # go existed at selection time (no extra directory RPC:
                # probed against the SAME row snapshot).
                others = (not final) and self._select_source(
                    object_id, tried | {target}, eff_seq,
                    rows=rows) is not None
                if self._fetch_from(object_id, target, tried, eff_seq,
                                    others_available=others):
                    return True
                # Only a GENUINE relay failure counts toward the cap: a
                # load-reselect took the target back out of ``tried``
                # (it was merely busy, not dead).
                if row.get("partial") and target in tried:
                    partial_failures += 1
            return False
        finally:
            if my_seq is not None:
                try:
                    self._directory.remove_partial_location(object_id,
                                                            local_id)
                except Exception:
                    pass

    def _fetch_from(self, object_id: ObjectID, node_id: NodeID,
                    tried: set, my_seq: Optional[int] = None,
                    others_available: bool = False) -> bool:
        """Streamed transfer of the serialized object from a remote node
        store into the local store (ObjectBufferPool chunk assembly
        parity) — single-copy end to end:

        * cross-process peers: a WINDOWED pipeline of in-flight chunk
          requests (rpc/chunked.py) assembles each chunk directly into a
          reserved local shm-segment block — no intermediate
          ``bytearray``, no whole-blob RPC;
        * in-process source stores (simulated multi-node): the source's
          segment view is copied chunk-by-chunk straight into the local
          reservation under a source-side pin.

        Per-transfer throughput and the in-flight window peak are
        exported through the metrics agent.  Returns True only when the
        object is local afterwards; a False return left ``node_id`` in
        ``tried`` unless the source was merely busy (the caller's
        selection loop retries the others)."""
        tried.add(node_id)
        local_id = self._raylet.node_id
        if node_id == local_id or node_id is None:
            # The object landed locally since the caller's check
            # (concurrent put/restore) — or a None row from a timed-out
            # remote wait_object.
            return self._raylet.object_store.contains(object_id)
        source = self._raylet.cluster.gcs.raylet(node_id)
        if source is None:
            return False            # source died; caller tries others
        from ray_tpu.util import tracing
        from ray_tpu._private import worker_context
        # The consuming task, when this pull runs on an executor thread
        # materializing args (the critical-path engine's edge
        # attribution); pulls from pump threads carry no task.
        ctx_spec = worker_context.current_task_spec()
        transfer_span = tracing.span(
            "object.transfer", category="transfer",
            # Force-recorded when the profiler is armed: `ray-tpu
            # profile` needs edge-transfer time even when full tracing
            # is off (the span ring is bounded either way).
            force=get_config().job_profiler_enabled,
            node=self._raylet.node_id.hex()[:12],
            source=node_id.hex()[:12],
            object_id=object_id.hex(),
            task_id=ctx_spec.task_id.hex() if ctx_spec is not None else "")
        transfer_span.__enter__()
        t0 = time.monotonic()
        reader = source.object_store
        window_peak = [0]

        def on_chunk(nbytes: int, inflight: int):
            # Chaos point: per-chunk delay (slow network) or error
            # (truncated transfer -> abort + retry path).
            fault_injection.hook("transfer.chunk")
            self.stats["chunks_transferred"] += 1
            if inflight > window_peak[0]:
                window_peak[0] = inflight

        try:
            if hasattr(reader, "fetch_into"):
                # Cross-process peer: pipelined chunk stream into the
                # local segment (PullManager admission + ack flow).
                # With other untried sources on the board (known from
                # the caller's row snapshot — no extra directory RPC),
                # bound the busy-retry patience so a saturated sender
                # makes us re-select instead of camping in its backoff
                # loop.
                patience = None
                if others_available:
                    patience = max(
                        2.0,
                        2 * get_config().object_transfer_admission_wait_s)
                nbytes = reader.fetch_into(
                    object_id, self._raylet.object_store,
                    pipeline=get_config().object_transfer_pipeline_depth,
                    on_chunk=on_chunk, busy_patience_s=patience)
            elif isinstance(reader, NodeObjectStore):
                nbytes = self._pull_in_process(
                    object_id, reader, node_id, tried, my_seq,
                    on_chunk, allow_reselect=others_available)
            else:
                nbytes = self._copy_via_serialized(object_id, reader,
                                                   on_chunk)
        except BaseException:
            transfer_span.meta["ok"] = False
            transfer_span.__exit__(None, None, None)
            raise
        if nbytes is self._RESELECT:
            # Busy source with a freer alternative: not a failure — the
            # caller re-ranks (the source was taken back OUT of tried).
            transfer_span.meta["ok"] = "reselect"
            transfer_span.__exit__(None, None, None)
            return False
        if nbytes is None:
            self.stats["failed_pulls"] += 1
            transfer_span.meta["ok"] = False
            transfer_span.__exit__(None, None, None)
            return False
        self.stats["pulled_objects"] += 1
        # The object is local either way — the location row is true
        # even when a racing transfer moved the bytes.
        self._directory.add_location(object_id, self._raylet.node_id,
                                     size=nbytes or None)
        if nbytes:
            # nbytes == 0 = the single-writer dedupe adopted a racing
            # transfer's copy: THIS pull moved no bytes — byte counters
            # and the transfer rate must not be booked for it.
            self.stats["pulled_bytes"] += nbytes
            self.stats["cross_node_fetch_bytes"] += nbytes
            elapsed = max(time.monotonic() - t0, 1e-9)
            self.stats["transfer_gbps_last"] = round(
                nbytes / elapsed / 1e9, 3)
            from ray_tpu._private.metrics_agent import (observe_internal,
                                                        record_internal)
            record_internal("ray_tpu.object_manager.transfer_gbps",
                            nbytes / elapsed / 1e9,
                            node=self._raylet.node_id.hex()[:12])
            observe_internal("ray_tpu.object_manager.transfer_seconds",
                             elapsed)
        self.stats["inflight_window_peak"] = max(
            self.stats["inflight_window_peak"], window_peak[0])
        transfer_span.meta["bytes"] = nbytes
        transfer_span.__exit__(None, None, None)
        return True

    def _pull_in_process(self, object_id: ObjectID,
                         src: "NodeObjectStore", node_id: NodeID,
                         tried: set, my_seq: Optional[int], on_chunk,
                         allow_reselect: bool = True):
        """In-process store-to-store pull under sender admission:
        FIFO-queue on the source's outbound ledger, but keep probing
        for a source with a FREE slot while queued — a relay one hop
        downstream beats waiting behind the origin's queue, which is
        exactly what turns a simultaneous 1→N burst into a pipelined
        chain.  Returns the byte count, None on failure, or
        ``_RESELECT`` (the caller re-ranks; this source stays
        un-tried)."""
        ledger = getattr(src, "transfer_ledger", None)
        if ledger is None:
            return self._copy_local(object_id, src, on_chunk)
        deadline = time.monotonic() + 300.0
        # One ticket for the whole wait: the FIFO position is KEPT
        # across the bounded polls the better-source probes ride on
        # (re-enqueueing per poll would let steady remote admits starve
        # an in-process waiter forever).
        ticket = ledger.enqueue()
        while not ledger.wait_grant(ticket, timeout=0.25):
            if time.monotonic() >= deadline:
                ledger.cancel(ticket)
                return None
            if not allow_reselect:
                continue        # final patient round: queue to grant
            better = self._select_source(object_id, tried, my_seq,
                                         require_free=True)
            if better is not None:
                # Leave this source's queue without branding it failed.
                ledger.cancel(ticket)
                tried.discard(node_id)
                self.stats["load_reselects"] += 1
                return self._RESELECT
        try:
            relay = None
            if not src.contains(object_id):
                relay = src.open_relay_source(object_id)
            if relay is not None:
                nbytes = self._relay_copy_local(object_id, relay,
                                                on_chunk)
                if nbytes:
                    ledger.note_served(nbytes, relay=True)
                    self.stats["relay_pulls"] += 1
                return nbytes
            nbytes = self._copy_local(object_id, src, on_chunk)
            if nbytes:
                ledger.note_served(nbytes)
            return nbytes
        finally:
            ledger.release()

    def _relay_copy_local(self, object_id: ObjectID, relay,
                          on_chunk) -> Optional[int]:
        """Chunk-copy the assembled prefix of a peer's IN-FLIGHT
        transfer into a local reservation, chasing its watermark — the
        in-process leg of chain relay.  An upstream abort fails this
        transfer cleanly (writer aborted, caller re-selects); a stalled
        upstream is abandoned after a progress timeout."""
        nbytes = relay.nbytes
        store = self._raylet.object_store
        writer = store.create_transfer_writer(object_id, nbytes)
        if writer is None:
            return 0             # a concurrent pull already delivered it
        chunk = get_config().object_manager_chunk_size
        step_wait = max(get_config().object_transfer_relay_wait_s, 0.1)
        try:
            off = 0
            last_progress = time.monotonic()
            while off < nbytes:
                end = min(off + chunk, nbytes)
                try:
                    data = relay.read_range(off, end, timeout=step_wait)
                except TimeoutError:
                    # Upstream alive but not yet past ``end``: keep
                    # chasing, bounded by a no-progress cap.
                    if time.monotonic() - last_progress > 60.0:
                        writer.abort()
                        return None
                    continue
                except Exception:
                    writer.abort()
                    return None
                if data is None:          # upstream transfer died
                    writer.abort()
                    return None
                writer.write(off, data)
                on_chunk(len(data), 0)
                off = end
                last_progress = time.monotonic()
            writer.seal()
        except BaseException:
            writer.abort()
            raise
        return nbytes

    def _copy_local(self, object_id: ObjectID, src: "NodeObjectStore",
                    on_chunk) -> Optional[int]:
        """In-process store-to-store transfer: chunked copy from the
        source's segment view directly into a local reservation.  The
        source block is pinned for the duration so eviction cannot
        recycle it mid-read.  A SPILLED source is served straight from
        its spill-file mmap — the transfer never forces the sender to
        restore the bytes into its store budget."""
        spilled = src.open_spilled_view(object_id)
        if spilled is not None:
            view, release = spilled
            try:
                return self._chunk_copy_into_local(object_id, view,
                                                   on_chunk)
            finally:
                release()
        entry = src.get(object_id)
        if entry is None:
            return None
        data = entry.data
        if isinstance(data, _NativeHandle) and src._native is not None:
            key = data.key
            # Pin failure = the block was spilled/freed in the window;
            # fall through to the serialized leg, whose get() restores
            # spilled bytes — the object may still be recoverable.
            if src._native.pin(key):
                try:
                    view = data.read()
                    if view is not None:
                        return self._chunk_copy_into_local(
                            object_id, view, on_chunk)
                finally:
                    src._native.unpin(key)
        return self._copy_via_serialized(object_id, src, on_chunk)

    def _chunk_copy_into_local(self, object_id: ObjectID, view,
                               on_chunk) -> int:
        """Chunk-copy a flat source view (pinned segment block or
        spill-file mmap) into a reserved local store block."""
        nbytes = view.nbytes
        store = self._raylet.object_store
        writer = store.create_transfer_writer(object_id, nbytes)
        if writer is None:
            return 0             # a concurrent pull already delivered it
        try:
            chunk = get_config().object_manager_chunk_size
            for off in range(0, nbytes, chunk):
                writer.write(off, view[off:off + chunk])
                on_chunk(min(chunk, nbytes - off), 0)
            writer.seal()
        except BaseException:
            writer.abort()
            raise
        return nbytes

    def _copy_via_serialized(self, object_id: ObjectID, reader,
                             on_chunk) -> Optional[int]:
        """Generic leg (python-held / device / proxy sources): the
        source hands back a SerializedObject whose buffers are
        heap-backed (kept alive by the views), and the local put moves
        them straight into the local segment — still one data copy."""
        serialized = reader.get_serialized(object_id)
        if serialized is None:
            return None
        nbytes = serialized.flat_nbytes
        self._raylet.object_store.put(object_id, serialized, pin=False)
        chunk = get_config().object_manager_chunk_size
        for off in range(0, nbytes, chunk):
            on_chunk(min(chunk, nbytes - off), 0)
        return nbytes
