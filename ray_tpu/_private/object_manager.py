"""Object plane: location directory + node-to-node transfer.

Parity: reference ``src/ray/object_manager/`` — the
``OwnershipBasedObjectDirectory`` (owners are the source of truth for object
locations, ownership_based_object_directory.cc), ``PullManager``
(admission-controlled pulls with retry, pull_manager.cc) and ``PushManager``
(chunked pushes, push_manager.cc).  Transfers here copy the serialized bytes
chunk-by-chunk between node stores (object_manager_chunk_size), preserving
the chunked-flow structure the gRPC path would have.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Set

from ray_tpu import exceptions
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.serialization import SerializedObject


class ObjectDirectory:
    """Object location directory (ownership-based in the reference; the
    owner table lives with the driver core worker here and this directory
    is its queryable index)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._locations: Dict[ObjectID, Set[NodeID]] = {}
        self._subscribers: Dict[ObjectID, List[Callable]] = {}

    def add_location(self, object_id: ObjectID, node_id: NodeID):
        with self._lock:
            self._locations.setdefault(object_id, set()).add(node_id)
            subs = self._subscribers.pop(object_id, [])
        for cb in subs:
            cb(node_id)

    def remove_location(self, object_id: ObjectID, node_id: NodeID):
        with self._lock:
            locs = self._locations.get(object_id)
            if locs:
                locs.discard(node_id)
                if not locs:
                    del self._locations[object_id]

    def remove_object(self, object_id: ObjectID):
        with self._lock:
            self._locations.pop(object_id, None)
            # A freed object can never gain a location; drop its waiters
            # (wait() wakeup hooks would otherwise accumulate forever).
            self._subscribers.pop(object_id, None)

    def get_locations(self, object_id: ObjectID) -> Set[NodeID]:
        with self._lock:
            return set(self._locations.get(object_id, ()))

    def subscribe_location(self, object_id: ObjectID, cb: Callable):
        """Callback fired when the first location appears."""
        with self._lock:
            locs = self._locations.get(object_id)
            if locs:
                node = next(iter(locs))
            else:
                self._subscribers.setdefault(object_id, []).append(cb)
                return
        cb(node)

    def unsubscribe_location(self, object_id: ObjectID, cb: Callable):
        """Deregister a pending location subscription (no-op if it
        already fired or was never registered)."""
        with self._lock:
            subs = self._subscribers.get(object_id)
            if subs is None:
                return
            try:
                subs.remove(cb)
            except ValueError:
                return
            if not subs:
                del self._subscribers[object_id]

    def on_node_death(self, node_id: NodeID) -> List[ObjectID]:
        """Remove all locations on a dead node; returns objects that lost
        their last copy (candidates for lineage reconstruction)."""
        lost = []
        with self._lock:
            for oid, locs in list(self._locations.items()):
                if node_id in locs:
                    locs.discard(node_id)
                    if not locs:
                        del self._locations[oid]
                        lost.append(oid)
        return lost


class NodeObjectManager:
    """Per-node transfer manager (PullManager/PushManager parity)."""

    def __init__(self, raylet, directory: ObjectDirectory):
        self._raylet = raylet
        self._directory = directory
        self._lock = threading.Lock()
        self._inflight_pulls: Dict[ObjectID, List[Callable]] = {}
        # Transfers run on their own IO pool — a multi-GiB pull on the
        # raylet's event loop would stall its heartbeats and scheduling
        # ticks (the reference's pull manager runs on dedicated io
        # contexts for the same reason).  Daemon workers + stop():
        # in-flight pulls must not block process exit.
        from ray_tpu._private.daemon_pool import DaemonPool
        self._pull_pool = DaemonPool(
            4, name=f"ray_tpu::pull::{raylet.node_id.hex()[:6]}")
        self.stats = {"pulled_objects": 0, "pulled_bytes": 0,
                      "chunks_transferred": 0}

    # ---- queries --------------------------------------------------------
    def is_local_or_inline(self, object_id: ObjectID) -> bool:
        if self._raylet.object_store.contains(object_id):
            return True
        # Small objects live in the owner's in-process memory store and are
        # readable from any node in-process ("inlined in PushTask").  An
        # InPlasmaMarker does NOT count: the real bytes are on some node
        # and must be pulled.
        core = self._raylet.core_worker
        if core is None:
            return False
        from ray_tpu._private.object_store import InPlasmaMarker
        entry = core.memory_store.get_entry(object_id)
        return entry is not None and entry.sealed and \
            not isinstance(entry.data, InPlasmaMarker)

    # ---- pull path ------------------------------------------------------
    def pull_async(self, object_id: ObjectID, cb: Callable[[bool], None]):
        if self.is_local_or_inline(object_id):
            cb(True)
            return
        with self._lock:
            waiters = self._inflight_pulls.get(object_id)
            if waiters is not None:
                waiters.append(cb)
                return
            self._inflight_pulls[object_id] = [cb]

        def finish(ok: bool):
            with self._lock:
                waiters = self._inflight_pulls.pop(object_id, None)
            if waiters is None:
                return  # another path already finished this pull
            for w in waiters:
                w(ok)

        def attempt(node_id):
            if self.is_local_or_inline(object_id):
                finish(True)
                return
            finish(self._fetch_from(object_id, node_id))

        locations = self._directory.get_locations(object_id)
        if locations:
            self._pull_pool.submit(attempt, next(iter(locations)))
            return
        # Freed object: nothing will ever produce it again — fail fast
        # instead of subscribing forever (the caller may try lineage
        # reconstruction).
        core = self._raylet.core_worker
        if core is not None and \
                not core.reference_counter.has_reference(object_id) and \
                not core.task_manager.is_pending(object_id.task_id()):
            finish(False)
            return
        # No location yet: the object may still be computing.  Watch both
        # signals — a directory location (big objects land in a node store)
        # and the owner's memory store (small returns are "inlined" there,
        # never registered with the directory) — first one wins.  Mirrors
        # the pull manager's retry loop + memory-store GetAsync.
        self._directory.subscribe_location(
            object_id,
            lambda node_id: self._pull_pool.submit(attempt, node_id))
        core = self._raylet.core_worker
        if core is not None:
            core.memory_store.get_async(
                object_id, lambda entry: finish(True))

    def stop(self):
        self._pull_pool.stop()

    def _fetch_from(self, object_id: ObjectID, node_id: NodeID) -> bool:
        """Chunked copy of the serialized object from a remote node store
        into the local store (ObjectBufferPool chunk assembly parity)."""
        source = self._raylet.cluster.gcs.raylet(node_id)
        if source is None:
            # Source died; try another location or give up.
            for other in self._directory.get_locations(object_id):
                if other != node_id:
                    return self._fetch_from(object_id, other)
            return False
        serialized = source.object_store.get_serialized(object_id)
        if serialized is None:
            return False
        blob = serialized.to_bytes()
        chunk = get_config().object_manager_chunk_size
        assembled = bytearray(len(blob))
        for off in range(0, len(blob), chunk):
            assembled[off:off + chunk] = blob[off:off + chunk]
            self.stats["chunks_transferred"] += 1
        restored = SerializedObject.from_bytes(bytes(assembled))
        self._raylet.object_store.put(object_id, restored, pin=False)
        self._directory.add_location(object_id, self._raylet.node_id)
        self.stats["pulled_objects"] += 1
        self.stats["pulled_bytes"] += len(blob)
        return True
