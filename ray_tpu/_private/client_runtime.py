"""Client-mode runtime: the full public API from inside a process-mode
worker, proxied through the worker-host service.

Parity: the reference's in-worker CoreWorker — every worker process runs
its own submission/ownership client talking to its raylet and the GCS
(``core_worker.cc`` in non-driver mode).  Here the child builds real
TaskSpecs locally (it has the same spec machinery as the driver) and
ships them to the host, whose core worker owns the resulting objects:
nested ``.remote`` calls, ``put/get/wait``, actor creation and method
calls, named-actor lookup, and ``kill`` all work inside process-mode
workers.

Installed by ``worker_main`` right after registration:
``install(host_client)`` populates the process-global worker singleton,
so user code just calls ``ray_tpu.*``.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, List, Optional, Sequence, Tuple

from ray_tpu import exceptions
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.serialization import (
    SerializedObject, deserialize, serialize)
from ray_tpu._private.task_spec import TaskArg


class _ClientKV:
    """GCS KV slice used by runtime-env normalization in the child."""

    def __init__(self, client):
        self._client = client

    def get(self, key: bytes, namespace=None):
        return self._client.call("kv_get", key, timeout=30.0)

    def put(self, key: bytes, value: bytes, overwrite: bool = True,
            namespace=None) -> bool:
        return self._client.call(
            "kv_put", {"key": key, "value": value, "overwrite": overwrite},
            timeout=60.0)


class _ClientActorRecord:
    """Duck-types the GcsActor slice ``actor.py`` reads (creation_spec,
    class name) for method submission."""

    def __init__(self, record: dict):
        self.actor_id = record["actor_id"]
        self.state = record.get("state")
        self.num_restarts = record.get("num_restarts", 0)
        self._info = {"class_name": record.get("class_name", "")}
        self.creation_spec = pickle.loads(record["spec_blob"]) \
            if record.get("spec_blob") else None

    def info(self):
        return dict(self._info)


class _ClientActorManager:
    def __init__(self, client):
        self._client = client

    def get_actor(self, actor_id):
        record = self._client.call("actor_info", {"actor_id": actor_id},
                                   timeout=30.0)
        return None if record is None else _ClientActorRecord(record)

    def get_named_actor(self, name: str, namespace: str = ""):
        record = self._client.call(
            "named_actor_info", {"name": name, "namespace": namespace},
            timeout=30.0)
        return None if record is None else _ClientActorRecord(record)

    def destroy_actor(self, actor_id, no_restart: bool = True):
        self._client.call("kill_actor",
                          {"actor_id": actor_id, "no_restart": no_restart},
                          timeout=30.0)


class _ClientGcs:
    def __init__(self, client):
        self.kv = _ClientKV(client)
        self.actor_manager = _ClientActorManager(client)


class _NodeStub:
    __slots__ = ("node_id",)

    def __init__(self, node_id):
        self.node_id = node_id


class _ClientCluster:
    """The cluster surface the public API layer touches in client mode."""

    def __init__(self, client, info: dict):
        self.gcs = _ClientGcs(client)
        # runtime_context reads cluster.head_node.node_id when no task
        # context is set; from a worker, "here" is the hosting node.
        self.head_node = _NodeStub(info.get("node_id"))


class _NullReferenceCounter:
    """Ownership lives host-side; client-side handles don't refcount
    (deserialized refs register here so the shared ObjectRef machinery
    works unchanged)."""

    def add_local_ref(self, _oid):
        pass

    def remove_local_ref(self, _oid):
        pass

    def release_local_ref_async(self, _oid):
        # ObjectRef.__del__ calls this on every registered ref's GC; it
        # must exist (not merely be swallowed as AttributeError).
        pass

    def add_borrowed_object(self, _oid, borrower=None):
        pass

    def has_reference(self, _oid) -> bool:
        return True


class ClientCoreWorker:
    """Duck-types the CoreWorker methods the API layer calls, proxying
    submission/ownership to the host's core worker."""

    is_driver = False

    def __init__(self, client, info: dict, client_worker_id: str = ""):
        self._client = client
        self.job_id = info["job_id"]
        self.worker_id = info["owner_id"]      # ownership stays host-side
        self.client_worker_id = client_worker_id   # pin scope on the host
        self.driver_task_id = TaskID.for_driver(self.job_id)
        # The real FunctionManager over the client KV: identical export
        # semantics (incl. keeping exported fns alive so id() reuse can't
        # alias a stale digest).
        from ray_tpu._private.function_manager import FunctionManager
        self.function_manager = FunctionManager(_ClientKV(client))
        self.reference_counter = _NullReferenceCounter()
        self.cluster = _ClientCluster(client, info)

    # ---- args / submission ---------------------------------------------
    def build_args(self, flat_args):
        cfg = get_config()
        out: List[TaskArg] = []
        dep_ids: List[ObjectID] = []
        holders: List[ObjectRef] = []
        borrowed: List[ObjectID] = []
        for a in flat_args:
            if isinstance(a, ObjectRef):
                out.append(TaskArg(is_inline=False,
                                   object_id=a.object_id(),
                                   owner_id=a.owner_id()))
                dep_ids.append(a.object_id())
            else:
                s = serialize(a)
                if s.total_bytes > cfg.task_args_inline_bytes_limit:
                    ref = self.put(a)
                    holders.append(ref)
                    out.append(TaskArg(is_inline=False,
                                       object_id=ref.object_id(),
                                       owner_id=ref.owner_id()))
                    dep_ids.append(ref.object_id())
                else:
                    borrowed.extend(r.object_id()
                                    for r in s.contained_refs)
                    out.append(TaskArg(is_inline=True, value=s))
        return out, dep_ids, holders, borrowed

    def _inject_trace_ctx(self, spec) -> None:
        """Stamp ``TaskSpec.trace_ctx`` exactly like the in-process
        submit path does (core_worker.py submit_task) — WITHOUT this, a
        nested ``.remote`` from inside a process-mode worker (or any
        ray-client driver) started a fresh trace and the driver →
        actor-method → nested-task chain broke at the process boundary.
        ``force`` when a parent context exists: the enclosing execute
        span is force-recorded in workers that never enabled capture,
        and the submit hop must be too."""
        from ray_tpu.util import tracing
        parent = tracing.current_context()
        with tracing.span(f"submit:{spec.function_name}",
                          category="submit", parent=parent,
                          force=bool(parent),
                          task_id=spec.task_id.hex()) as sp:
            spec.trace_ctx = sp.context()

    def submit_task(self, spec, holders=()) -> List[ObjectRef]:
        # worker_id scopes the host-side pin on the RESULT objects to
        # this client (released with the client, like put pins).
        self._inject_trace_ctx(spec)
        self._client.call("submit_task",
                          {"spec": spec,
                           "worker_id": self.client_worker_id},
                          timeout=60.0)
        del holders
        return [ObjectRef(oid, owner_id=self.worker_id,
                          skip_adding_local_ref=True)
                for oid in spec.return_ids]

    def submit_actor_task(self, spec, holders=()) -> List[ObjectRef]:
        self._inject_trace_ctx(spec)
        self._client.call("submit_actor_task",
                          {"spec": spec,
                           "worker_id": self.client_worker_id},
                          timeout=60.0)
        del holders
        return [ObjectRef(oid, owner_id=self.worker_id,
                          skip_adding_local_ref=True)
                for oid in spec.return_ids]

    def create_actor(self, creation_spec, name: str = "",
                     namespace: str = "", detached: bool = False):
        self._client.call("create_actor", {
            "spec": creation_spec, "name": name, "namespace": namespace,
            "detached": detached}, timeout=60.0)

    # ---- objects ---------------------------------------------------------
    def put(self, value: Any, _owner=None) -> ObjectRef:
        reply = self._client.call(
            "put_object", {"blob": serialize(value).to_bytes(),
                           "worker_id": self.client_worker_id},
            timeout=300.0)
        return ObjectRef(reply["object_id"], owner_id=reply["owner_id"],
                         skip_adding_local_ref=True)

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        """Overall deadline across ALL refs (reference ray.get
        semantics), with the host round-trips issued concurrently."""
        import time

        deadline = None if timeout is None else \
            time.monotonic() + timeout
        futures = [self._client.call_future(
            "get_value", {"object_id": ref.object_id(),
                          "timeout": timeout})
            for ref in refs]
        out = []
        for ref, fut in zip(refs, futures):
            remaining = None if deadline is None else \
                max(0.0, deadline - time.monotonic()) + 30.0
            try:
                result = fut.result(timeout=remaining)
            except TimeoutError:
                raise exceptions.GetTimeoutError(
                    f"Get timed out for {ref.object_id()}")
            if result is None:
                raise exceptions.GetTimeoutError(
                    f"Get timed out for {ref.object_id()}")
            kind, blob = result
            if kind == "error":
                err = pickle.loads(blob)
                if isinstance(err, exceptions.TaskError):
                    raise err.as_instanceof_cause()
                raise err
            if kind == "chunked":
                from ray_tpu.rpc.chunked import fetch_session
                blob = fetch_session(self._client, blob, timeout=600.0)
                if blob is None:
                    raise exceptions.ObjectLostError(
                        ref.object_id(), "chunked client fetch failed")
            out.append(deserialize(SerializedObject.from_bytes(blob)))
        return out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None,
             fetch_local: bool = True) -> Tuple[List, List]:
        reply = self._client.call(
            "wait_refs",
            {"object_ids": [r.object_id() for r in refs],
             "num_returns": num_returns, "timeout": timeout},
            timeout=None if timeout is None else timeout + 30.0)
        by_id = {r.object_id(): r for r in refs}
        return ([by_id[oid] for oid in reply["ready"]],
                [by_id[oid] for oid in reply["not_ready"]])

    def get_async(self, ref: ObjectRef, callback):
        def run():
            try:
                callback(self.get([ref])[0], None)
            except BaseException as e:    # noqa: BLE001
                callback(None, e)

        threading.Thread(target=run, daemon=True).start()


def install(host_client, info: Optional[dict] = None,
            client_worker_id: str = ""):
    """Connect this process's global worker to the host: after this,
    ``ray_tpu.*`` works inside the process-mode worker."""
    info = info or host_client.call("runtime_info", None, timeout=30.0)
    core = ClientCoreWorker(host_client, info,
                            client_worker_id=client_worker_id)
    w = worker_mod.global_worker()
    w.core_worker = core
    w.cluster = core.cluster
    w.job_id = core.job_id
    w.namespace = info.get("namespace", "")
    w.mode = "client"
    w.connected = True
    return core
