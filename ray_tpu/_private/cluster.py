"""The in-process cluster: GCS + raylets + object directory.

Parity: reference ``python/ray/cluster_utils.py:100`` (``Cluster`` — multi
node without real machines: extra raylets/GCS as local entities with
distinct node ids; ``add_node`` :166, ``remove_node`` :235) — the backbone
of the reference's multi-node test strategy (SURVEY.md §4a) and of this
framework's simulated deployments.  A real multi-host deployment replaces
the direct method calls with the gRPC transport in front of the same
Raylet/GcsServer surfaces.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.object_manager import ObjectDirectory
from ray_tpu._private.raylet import Raylet
from ray_tpu.gcs.server import GcsServer
from ray_tpu._private.debug import diag_lock


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 gcs_storage_path: Optional[str] = None):
        self._gcs_storage_path = gcs_storage_path
        self.gcs = GcsServer(storage_path=gcs_storage_path)
        self.object_directory = ObjectDirectory()
        self._lock = diag_lock("Cluster._lock")
        self._raylets: List[Raylet] = []
        # EVERY in-process raylet ever created, including ones later
        # declared dead (heartbeat timeout) and dropped from
        # membership: shutdown must still stop their worker pools /
        # monitors, or process workers and log-monitor refs leak.
        self._ever_raylets: List[Raylet] = []
        self.head_node: Optional[Raylet] = None
        self.core_worker = None
        self.head_service = None          # wire front, started on demand
        self._remote_procs: List = []     # spawned NodeHost OS processes
        self.gcs.subscribe_node_death(self._on_node_death)
        if initialize_head:
            self.head_node = self.add_node(**(head_node_args or {}))

    # ---- membership -----------------------------------------------------
    @staticmethod
    def _assemble_totals(num_cpus=None, num_tpus=0.0, num_gpus=0.0,
                         memory=None, object_store_memory=None,
                         resources=None) -> Dict[str, float]:
        """One resource-dict builder for both in-process and remote
        nodes, so their defaults can never drift apart."""
        import os
        total: Dict[str, float] = {}
        total["CPU"] = float(num_cpus) if num_cpus is not None \
            else float(os.cpu_count() or 1)
        if num_tpus:
            total["TPU"] = float(num_tpus)
        if num_gpus:
            total["GPU"] = float(num_gpus)
        total["memory"] = memory if memory is not None else 4 * 1024**3
        total["object_store_memory"] = float(
            object_store_memory or get_config().object_store_memory)
        total.update(resources or {})
        return total

    def add_node(self, num_cpus: Optional[float] = None,
                 num_tpus: float = 0, num_gpus: float = 0,
                 memory: Optional[float] = None,
                 object_store_memory: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 node_name: str = "", labels: Optional[Dict] = None) -> Raylet:
        total = self._assemble_totals(num_cpus, num_tpus, num_gpus, memory,
                                      object_store_memory, resources)
        raylet = Raylet(self, total, node_name=node_name, labels=labels,
                        object_store_memory=object_store_memory)
        raylet.core_worker = self.core_worker
        with self._lock:
            self._raylets.append(raylet)
            self._ever_raylets.append(raylet)
        self.gcs.register_raylet(raylet)
        return raylet

    def adopt_raylet(self, raylet):
        """Register an externally-constructed raylet (a RemoteNodeProxy
        mirroring a NodeHost OS process) into the membership — the
        head-side half of NodeInfoGcsService.RegisterNode.  A
        re-registration of the same node id (a fenced node coming back
        as a fresh incarnation) REPLACES the stale mirror."""
        with self._lock:
            self._raylets = [r for r in self._raylets
                             if r.node_id != raylet.node_id]
            self._raylets.append(raylet)
            self._ever_raylets.append(raylet)
        self.gcs.register_raylet(raylet)

    def start_head_service(self, port: int = 0):
        """Start (once) the wire front that NodeHost processes join."""
        if self.head_service is None:
            from ray_tpu._private.head_service import HeadService
            self.head_service = HeadService(self, port=port)
        return self.head_service.address

    def add_remote_node(self, num_cpus: float = 1, num_tpus: float = 0,
                        num_gpus: float = 0,
                        memory: Optional[float] = None,
                        object_store_memory: Optional[int] = None,
                        resources: Optional[Dict[str, float]] = None,
                        node_name: str = "",
                        timeout: float = 30.0) -> "RemoteNodeHandle":
        """Spawn a worker-host OS process (``python -m
        ray_tpu._private.node_host``) and wait for it to register over
        TCP.  Reference: ``Cluster.add_node`` backed by a real raylet
        process instead of an in-process one.  The spawned process is
        matched by a one-shot registration token, so duplicate
        node_names cannot bind the handle to the wrong node."""
        return self.add_remote_nodes(
            [dict(num_cpus=num_cpus, num_tpus=num_tpus, num_gpus=num_gpus,
                  memory=memory, object_store_memory=object_store_memory,
                  resources=resources, node_name=node_name)],
            timeout=timeout)[0]

    def _spawn_node_host(self, spec: dict):
        """Spawn one NodeHost OS process; returns ``(proc, reg_token,
        name)`` without waiting for registration."""
        import json
        import os
        import subprocess
        import sys
        import uuid

        from ray_tpu._private.runtime_env import framework_import_root
        host, port = self.start_head_service()
        total = self._assemble_totals(
            spec.get("num_cpus", 1), spec.get("num_tpus", 0),
            spec.get("num_gpus", 0), spec.get("memory"),
            spec.get("object_store_memory"), spec.get("resources"))
        name = spec.get("node_name") or f"remote-{uuid.uuid4().hex[:8]}"
        reg_token = uuid.uuid4().hex
        env = dict(os.environ)
        env["PYTHONPATH"] = framework_import_root() + os.pathsep + \
            env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_host",
             "--head", f"{host}:{port}",
             "--resources", json.dumps(total),
             "--name", name,
             "--reg-token", reg_token,
             "--system-config", get_config().to_json()],
            env=env)
        return proc, reg_token, name

    def add_remote_nodes(self, specs, timeout: float = 60.0,
                         spawn_interval_s: float = 0.0
                         ) -> List["RemoteNodeHandle"]:
        """Spawn MANY NodeHost processes concurrently, then wait for
        them all to register.  Spawning everything before waiting is
        what makes a 50–64-host fleet stand up in seconds instead of
        serial spawn×poll round trips — and it deliberately produces
        the registration storm the head's admission gate
        (``head_registration_concurrency``) has to absorb.  On
        timeout/early-exit, already-spawned unregistered processes are
        killed and the error names the failing node."""
        import time

        entries = []           # (proc, reg_token, name, node_id|None)
        try:
            for spec in specs:
                proc, reg_token, name = self._spawn_node_host(spec)
                entries.append([proc, reg_token, name, None])
                if spawn_interval_s > 0:
                    time.sleep(spawn_interval_s)
            deadline = time.monotonic() + timeout
            pending = list(entries)
            while pending and time.monotonic() < deadline:
                still = []
                for e in pending:
                    node_id = self.head_service.node_id_for_token(e[1])
                    if node_id is not None:
                        e[3] = node_id
                        continue
                    if e[0].poll() is not None:
                        raise RuntimeError(
                            f"node_host {e[2]!r} exited with "
                            f"{e[0].returncode} before registering")
                    still.append(e)
                pending = still
                if pending:
                    time.sleep(0.02)
            if pending:
                raise TimeoutError(
                    f"{len(pending)}/{len(entries)} remote nodes failed "
                    f"to register within {timeout}s (first: "
                    f"{pending[0][2]!r})")
        except Exception:
            from ray_tpu._private.debug import swallow
            for proc, _tok, _name, node_id in entries:
                if node_id is None:
                    try:
                        proc.kill()
                    except Exception as kill_err:
                        swallow.noted("cluster.add_remote_nodes.kill",
                                      kill_err)
            raise
        handles = [RemoteNodeHandle(self, proc, node_id, name)
                   for proc, _tok, name, node_id in entries]
        with self._lock:
            self._remote_procs.extend(handles)
        return handles

    def remove_node(self, raylet: Raylet, graceful: bool = True):
        with self._lock:
            if raylet in self._raylets:
                self._raylets.remove(raylet)
        if graceful:
            raylet.shutdown()
        else:
            self.kill_node(raylet)

    def kill_node(self, raylet: Raylet):
        """Hard kill (no heartbeats, no dereg) — the GCS heartbeat manager
        declares it dead after num_heartbeats_timeout misses."""
        with self._lock:
            if raylet in self._raylets:
                self._raylets.remove(raylet)
        raylet.kill()

    def raylets(self) -> List[Raylet]:
        with self._lock:
            return list(self._raylets)

    # ---- driver wiring --------------------------------------------------
    def attach_core_worker(self, core_worker):
        self.core_worker = core_worker
        with self._lock:
            for r in self._raylets:
                r.core_worker = core_worker

    def _on_node_death(self, node_id: NodeID):
        with self._lock:
            self._raylets = [r for r in self._raylets
                             if r.node_id != node_id]
        lost = self.object_directory.on_node_death(node_id)
        if self.core_worker is not None:
            self.core_worker.on_node_death(node_id, lost)

    def shutdown(self):
        with self._lock:
            everyone = list(self._ever_raylets)
        from ray_tpu._private.debug import swallow
        for r in everyone:          # Raylet.shutdown is idempotent
            try:
                r.shutdown()
            except Exception as e:
                swallow.noted("cluster.shutdown_raylet", e)
        with self._lock:
            handles, self._remote_procs = self._remote_procs, []
        for h in handles:
            h.terminate()
        if self.head_service is not None:
            self.head_service.stop()
            self.head_service = None
        self.gcs.shutdown()
        try:
            # Clean shutdown: drop this (driver/head) process's crash
            # files — evidence already surfaced; the disk copy exists
            # for SIGKILL forensics, which this is not.
            from ray_tpu._private.debug import watchdog
            watchdog.prune_own_crash_files()
        except Exception as e:
            swallow.noted("cluster.wedge_prune", e)

    def restart_gcs(self):
        """Kill and restart the control plane over the same persistent
        storage, then reconcile it against the still-running raylets —
        the test surface of ``test_gcs_fault_tolerance.py``.  Requires a
        file-backed GCS (``gcs_storage_path``)."""
        if self._gcs_storage_path is None:
            raise ValueError("restart_gcs requires gcs_storage_path "
                             "(the in-memory store dies with the GCS)")
        self.gcs.shutdown()
        self.gcs = GcsServer(storage_path=self._gcs_storage_path)
        self.gcs.subscribe_node_death(self._on_node_death)
        self.gcs.reconcile(self.raylets())
        if self.core_worker is not None:
            self.core_worker.actor_submitter.on_gcs_restart()
        return self.gcs

    def proxy_for(self, node_id: NodeID):
        """The RemoteNodeProxy currently mirroring ``node_id`` (None for
        in-process raylets)."""
        raylet = self.gcs.raylet(node_id)
        return raylet if getattr(raylet, "is_remote_proxy", False) else None

    def wait_for_nodes(self, count: int, timeout: float = 10.0) -> bool:
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.gcs.node_manager.alive_nodes) >= count:
                return True
            time.sleep(0.01)
        return False


class RemoteNodeHandle:
    """Driver-side handle on a spawned NodeHost OS process."""

    def __init__(self, cluster: Cluster, proc, node_id: NodeID, name: str):
        self.cluster = cluster
        self.proc = proc
        self.node_id = node_id
        self.node_name = name

    @property
    def proxy(self):
        return self.cluster.proxy_for(self.node_id)

    def kill(self):
        """Hard kill the OS process: no dereg, no more heartbeats — the
        GCS declares the node dead after num_heartbeats_timeout misses
        (NodeKillerActor chaos parity, but with a real process)."""
        try:
            self.proc.kill()
            self.proc.wait(timeout=10.0)
        except Exception:
            pass

    def terminate(self):
        """Graceful stop: ask the node to shut down, then reap it."""
        proxy = self.proxy
        if proxy is not None:
            proxy.shutdown()
        try:
            self.proc.wait(timeout=5.0)
        except Exception:
            self.kill()
