"""The in-process cluster: GCS + raylets + object directory.

Parity: reference ``python/ray/cluster_utils.py:100`` (``Cluster`` — multi
node without real machines: extra raylets/GCS as local entities with
distinct node ids; ``add_node`` :166, ``remove_node`` :235) — the backbone
of the reference's multi-node test strategy (SURVEY.md §4a) and of this
framework's simulated deployments.  A real multi-host deployment replaces
the direct method calls with the gRPC transport in front of the same
Raylet/GcsServer surfaces.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.object_manager import ObjectDirectory
from ray_tpu._private.raylet import Raylet
from ray_tpu.gcs.server import GcsServer


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 gcs_storage_path: Optional[str] = None):
        self.gcs = GcsServer(storage_path=gcs_storage_path)
        self.object_directory = ObjectDirectory()
        self._lock = threading.Lock()
        self._raylets: List[Raylet] = []
        self.head_node: Optional[Raylet] = None
        self.core_worker = None
        self.gcs.subscribe_node_death(self._on_node_death)
        if initialize_head:
            self.head_node = self.add_node(**(head_node_args or {}))

    # ---- membership -----------------------------------------------------
    def add_node(self, num_cpus: Optional[float] = None,
                 num_tpus: float = 0, num_gpus: float = 0,
                 memory: Optional[float] = None,
                 object_store_memory: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 node_name: str = "", labels: Optional[Dict] = None) -> Raylet:
        import os
        total: Dict[str, float] = {}
        total["CPU"] = num_cpus if num_cpus is not None else (os.cpu_count() or 1)
        if num_tpus:
            total["TPU"] = num_tpus
        if num_gpus:
            total["GPU"] = num_gpus
        total["memory"] = memory if memory is not None else 4 * 1024**3
        total["object_store_memory"] = float(
            object_store_memory or get_config().object_store_memory)
        total.update(resources or {})
        raylet = Raylet(self, total, node_name=node_name, labels=labels,
                        object_store_memory=object_store_memory)
        raylet.core_worker = self.core_worker
        with self._lock:
            self._raylets.append(raylet)
        self.gcs.register_raylet(raylet)
        return raylet

    def remove_node(self, raylet: Raylet, graceful: bool = True):
        with self._lock:
            if raylet in self._raylets:
                self._raylets.remove(raylet)
        if graceful:
            raylet.shutdown()
        else:
            self.kill_node(raylet)

    def kill_node(self, raylet: Raylet):
        """Hard kill (no heartbeats, no dereg) — the GCS heartbeat manager
        declares it dead after num_heartbeats_timeout misses."""
        with self._lock:
            if raylet in self._raylets:
                self._raylets.remove(raylet)
        raylet.kill()

    def raylets(self) -> List[Raylet]:
        with self._lock:
            return list(self._raylets)

    # ---- driver wiring --------------------------------------------------
    def attach_core_worker(self, core_worker):
        self.core_worker = core_worker
        with self._lock:
            for r in self._raylets:
                r.core_worker = core_worker

    def _on_node_death(self, node_id: NodeID):
        with self._lock:
            self._raylets = [r for r in self._raylets
                             if r.node_id != node_id]
        lost = self.object_directory.on_node_death(node_id)
        if self.core_worker is not None:
            self.core_worker.on_node_death(node_id, lost)

    def shutdown(self):
        for r in self.raylets():
            r.shutdown()
        self.gcs.shutdown()

    def wait_for_nodes(self, count: int, timeout: float = 10.0) -> bool:
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.gcs.node_manager.alive_nodes) >= count:
                return True
            time.sleep(0.01)
        return False
