"""Per-task/actor runtime environments.

Parity: reference ``python/ray/_private/runtime_env/`` — validation
(``validation.py``), working-dir/py-modules packaging into the GCS KV
(``packaging.py``: zip + content-hash URI), and materialization on the
executing node (``working_dir.py``, ``py_modules.py``; driven by the
raylet's AgentManager ``GetOrCreateRuntimeEnv``,
``src/ray/raylet/agent_manager.h:49``).  The worker pool keys workers by
the env's stable hash (``src/ray/raylet/worker_pool.h:428``).

Supported fields: ``env_vars`` (dict), ``working_dir`` (local directory,
packaged + materialized), ``py_modules`` (list of local dirs, packaged +
put on the import path).  ``pip``/``conda`` are validated but rejected —
this image has no network egress; environments must be pre-baked.

Isolation depends on the worker mode: ``process`` workers get env vars /
cwd / import path injected at spawn (full isolation); ``thread`` workers
apply env vars around the task body under a global lock and extend
``sys.path`` (an approximation — use process mode for real isolation).
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import json
import os
import threading
import zipfile
from typing import Dict, List, Optional

_PKG_PREFIX = b"pkg:"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


class RuntimeEnvError(ValueError):
    pass


def validate(spec: dict) -> dict:
    """Normalize field types; reject the unsupported."""
    out = {}
    for key, value in (spec or {}).items():
        if key == "env_vars":
            if not isinstance(value, dict) or not all(
                    isinstance(k, str) and isinstance(v, str)
                    for k, v in value.items()):
                raise RuntimeEnvError("env_vars must be Dict[str, str]")
            out["env_vars"] = dict(value)
        elif key in ("working_dir", "py_modules"):
            out[key] = value
        elif key in ("pip", "conda"):
            raise RuntimeEnvError(
                f"runtime_env[{key!r}] is not supported: no network egress; "
                "bake dependencies into the image")
        else:
            raise RuntimeEnvError(f"Unknown runtime_env field {key!r}")
    return out


# ---------------------------------------------------------------------------
# Packaging (packaging.py parity: zip -> content-hash URI in the GCS KV)
# ---------------------------------------------------------------------------

def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for fname in sorted(files):
                full = os.path.join(root, fname)
                zf.write(full, os.path.relpath(full, path))
    return buf.getvalue()


def _dir_signature(path: str) -> str:
    """Cheap content fingerprint (relpath, size, mtime of every file) —
    walking metadata costs microseconds where re-zipping costs the full
    compression; lets hot submission loops skip repackaging."""
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
        for fname in sorted(files):
            full = os.path.join(root, fname)
            try:
                st = os.stat(full)
            except OSError:
                continue
            entries.append((os.path.relpath(full, path),
                            st.st_size, st.st_mtime_ns))
    return hashlib.sha256(repr(entries).encode()).hexdigest()


_package_cache: Dict[tuple, str] = {}
_package_cache_lock = threading.Lock()


def package_dir(path: str, kv) -> str:
    """Zip a local directory into the GCS KV; returns its content URI.
    Repeat submissions of an unchanged directory hit a signature cache
    instead of re-zipping (reference packaging.py caches per-URI)."""
    if not os.path.isdir(path):
        raise RuntimeEnvError(f"not a directory: {path!r}")
    key = (os.path.abspath(path), _dir_signature(path), id(kv))
    with _package_cache_lock:
        cached = _package_cache.get(key)
    if cached is not None:
        return cached
    blob = _zip_dir(path)
    digest = hashlib.sha256(blob).hexdigest()[:20]
    uri = f"pkg://{digest}"
    kv.put(_PKG_PREFIX + digest.encode(), blob, overwrite=False)
    with _package_cache_lock:
        _package_cache[key] = uri
    return uri


def framework_import_root() -> str:
    """Directory CONTAINING the ray_tpu package — prepend to a child
    process's PYTHONPATH so it can ``import ray_tpu`` from any cwd.
    The single definition for every process-spawn site."""
    import ray_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))


def normalize(spec: Optional[dict], kv) -> Optional[dict]:
    """Validate + package local paths into URIs + stamp the stable hash
    the worker pool keys on.  Call once at submission time."""
    if not spec:
        return None
    out = validate(spec)
    wd = out.get("working_dir")
    if wd and not str(wd).startswith("pkg://"):
        out["working_dir"] = package_dir(wd, kv)
    mods = out.get("py_modules")
    if mods:
        out["py_modules"] = [
            m if str(m).startswith("pkg://") else package_dir(m, kv)
            for m in mods]
    out["_hash"] = env_hash(out)
    return out


def env_hash(spec: Optional[dict]) -> str:
    if not spec:
        return ""
    canon = {k: v for k, v in spec.items() if k != "_hash"}
    return hashlib.sha256(
        json.dumps(canon, sort_keys=True).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# Materialization (working_dir.py / py_modules.py parity)
# ---------------------------------------------------------------------------

class RuntimeEnvContext:
    """A materialized environment: what a worker needs at exec time."""

    def __init__(self, env_vars: Dict[str, str], cwd: Optional[str],
                 import_paths: List[str]):
        self.env_vars = env_vars
        self.cwd = cwd
        self.import_paths = import_paths

    def spawn_env(self, base: Optional[dict] = None) -> Dict[str, str]:
        """Env dict for a process-mode worker spawn."""
        env = dict(base if base is not None else os.environ)
        env.update(self.env_vars)
        if self.import_paths:
            extra = os.pathsep.join(self.import_paths)
            env["PYTHONPATH"] = extra + os.pathsep + env.get("PYTHONPATH", "")
        if self.cwd:
            env["RAY_TPU_WORKER_CWD"] = self.cwd
        return env


def _extract_uri(uri: str, kv, dest_root: str) -> str:
    import fcntl

    digest = uri[len("pkg://"):]
    dest = os.path.join(dest_root, digest)
    marker = os.path.join(dest, ".materialized")
    if os.path.exists(marker):
        return dest
    # Cross-process/thread exclusion: concurrent materializations of the
    # same package must not extract over files a finished caller is
    # already importing from.
    os.makedirs(dest_root, exist_ok=True)
    with open(os.path.join(dest_root, f".{digest}.lock"), "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):
                return dest
            blob = kv.get(_PKG_PREFIX + digest.encode())
            if blob is None:
                raise RuntimeEnvError(f"package {uri} not found in GCS KV")
            os.makedirs(dest, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(dest)
            open(marker, "w").close()
            return dest
        finally:
            fcntl.flock(lock_f, fcntl.LOCK_UN)


def materialize(spec: Optional[dict], kv,
                dest_root: Optional[str] = None) -> RuntimeEnvContext:
    """Download + extract the env's packages on this node; idempotent
    per content hash (uri_cache.py parity)."""
    if not spec:
        return RuntimeEnvContext({}, None, [])
    from ray_tpu._private.config import get_config
    dest_root = dest_root or os.path.join(get_config().temp_dir,
                                          "runtime_env")
    cwd = None
    import_paths: List[str] = []
    wd = spec.get("working_dir")
    if wd:
        cwd = _extract_uri(wd, kv, dest_root)
        import_paths.append(cwd)
    for uri in spec.get("py_modules") or []:
        import_paths.append(_extract_uri(uri, kv, dest_root))
    return RuntimeEnvContext(dict(spec.get("env_vars") or {}), cwd,
                             import_paths)


# ---------------------------------------------------------------------------
# Thread-mode application (approximation; process mode is the real path)
# ---------------------------------------------------------------------------

_env_lock = threading.Lock()


@contextlib.contextmanager
def applied(ctx: RuntimeEnvContext):
    """Apply env vars (global, locked) and import paths around a task
    body in a thread-mode worker."""
    import sys
    if not ctx.env_vars and not ctx.import_paths:
        yield
        return
    with _env_lock:
        saved = {k: os.environ.get(k) for k in ctx.env_vars}
        os.environ.update(ctx.env_vars)
        added = [p for p in ctx.import_paths if p not in sys.path]
        sys.path[:0] = added
        try:
            yield
        finally:
            for p in added:
                try:
                    sys.path.remove(p)
                except ValueError:
                    pass
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
