"""Per-task/actor runtime environments.

Parity: reference ``python/ray/_private/runtime_env/`` — validation
(``validation.py``), working-dir/py-modules packaging into the GCS KV
(``packaging.py``: zip + content-hash URI), and materialization on the
executing node (``working_dir.py``, ``py_modules.py``; driven by the
raylet's AgentManager ``GetOrCreateRuntimeEnv``,
``src/ray/raylet/agent_manager.h:49``).  The worker pool keys workers by
the env's stable hash (``src/ray/raylet/worker_pool.h:428``).

Supported fields: ``env_vars`` (dict), ``working_dir`` (local directory,
packaged + materialized), ``py_modules`` (list of local dirs, packaged +
put on the import path), ``pip`` (requirement list; local wheel paths
are shipped through the KV and installed into a cached per-hash venv on
the executing node — reference ``runtime_env/pip.py``.  Name-only
requirements need network egress, which this image lacks: use local
wheels).  ``conda`` is rejected.

Isolation depends on the worker mode: ``process`` workers get env vars /
cwd / import path injected at spawn (full isolation); ``thread`` workers
apply env vars around the task body under a global lock and extend
``sys.path`` (an approximation — use process mode for real isolation).
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import json
import os
import threading
import zipfile
from typing import Dict, List, Optional

from ray_tpu._private.debug.lock_order import diag_lock

_PKG_PREFIX = b"pkg:"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


class RuntimeEnvError(ValueError):
    pass


def validate(spec: dict) -> dict:
    """Normalize field types; reject the unsupported."""
    out = {}
    for key, value in (spec or {}).items():
        if key == "env_vars":
            if not isinstance(value, dict) or not all(
                    isinstance(k, str) and isinstance(v, str)
                    for k, v in value.items()):
                raise RuntimeEnvError("env_vars must be Dict[str, str]")
            out["env_vars"] = dict(value)
        elif key in ("working_dir", "py_modules"):
            out[key] = value
        elif key == "pip":
            if isinstance(value, dict):
                value = value.get("packages", [])
            if not isinstance(value, (list, tuple)) or not all(
                    isinstance(r, str) for r in value):
                raise RuntimeEnvError(
                    "pip must be a list of requirement strings")
            out["pip"] = sorted(value)
        elif key == "conda":
            raise RuntimeEnvError(
                "runtime_env['conda'] is not supported: no network "
                "egress; use pip with local wheels, or bake "
                "dependencies into the image")
        else:
            raise RuntimeEnvError(f"Unknown runtime_env field {key!r}")
    return out


# ---------------------------------------------------------------------------
# Packaging (packaging.py parity: zip -> content-hash URI in the GCS KV)
# ---------------------------------------------------------------------------

def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for fname in sorted(files):
                full = os.path.join(root, fname)
                zf.write(full, os.path.relpath(full, path))
    return buf.getvalue()


def _dir_signature(path: str) -> str:
    """Cheap content fingerprint (relpath, size, mtime of every file) —
    walking metadata costs microseconds where re-zipping costs the full
    compression; lets hot submission loops skip repackaging."""
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
        for fname in sorted(files):
            full = os.path.join(root, fname)
            try:
                st = os.stat(full)
            except OSError:
                continue
            entries.append((os.path.relpath(full, path),
                            st.st_size, st.st_mtime_ns))
    return hashlib.sha256(repr(entries).encode()).hexdigest()


_package_cache: Dict[tuple, str] = {}
_package_cache_lock = diag_lock("runtime_env._package_cache_lock")


def package_dir(path: str, kv) -> str:
    """Zip a local directory into the GCS KV; returns its content URI.
    Repeat submissions of an unchanged directory hit a signature cache
    instead of re-zipping (reference packaging.py caches per-URI)."""
    if not os.path.isdir(path):
        raise RuntimeEnvError(f"not a directory: {path!r}")
    key = (os.path.abspath(path), _dir_signature(path), id(kv))
    with _package_cache_lock:
        cached = _package_cache.get(key)
    if cached is not None:
        return cached
    blob = _zip_dir(path)
    digest = hashlib.sha256(blob).hexdigest()[:20]
    uri = f"pkg://{digest}"
    kv.put(_PKG_PREFIX + digest.encode(), blob, overwrite=False)
    with _package_cache_lock:
        _package_cache[key] = uri
    return uri


def framework_import_root() -> str:
    """Directory CONTAINING the ray_tpu package — prepend to a child
    process's PYTHONPATH so it can ``import ray_tpu`` from any cwd.
    The single definition for every process-spawn site."""
    import ray_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))


def package_file(path: str, kv) -> str:
    """Store a single local file (e.g. a wheel) in the GCS KV; returns
    ``pkg://<digest>/<basename>`` so the materializing node can restore
    it under its original filename (pip needs the wheel name intact)."""
    with open(path, "rb") as f:
        blob = f.read()
    digest = hashlib.sha256(blob).hexdigest()[:20]
    kv.put(_PKG_PREFIX + digest.encode(), blob, overwrite=False)
    return f"pkg://{digest}/{os.path.basename(path)}"


def normalize(spec: Optional[dict], kv) -> Optional[dict]:
    """Validate + package local paths into URIs + stamp the stable hash
    the worker pool keys on.  Call once at submission time."""
    if not spec:
        return None
    out = validate(spec)
    wd = out.get("working_dir")
    if wd and not str(wd).startswith("pkg://"):
        out["working_dir"] = package_dir(wd, kv)
    mods = out.get("py_modules")
    if mods:
        out["py_modules"] = [
            m if str(m).startswith("pkg://") else package_dir(m, kv)
            for m in mods]
    if out.get("pip"):
        # Requirements that are local wheel files exist only on the
        # SUBMITTING machine — ship them through the KV so the
        # executing node can install offline (reference pip.py ships a
        # requirements file; local wheels are this image's only
        # network-free install source).
        packed = []
        for r in out["pip"]:
            if r.endswith(".whl") and not r.startswith("pkg://"):
                if not os.path.isfile(r):
                    # Fail at SUBMISSION, naming the file — deferring
                    # ships the bad path and errors in a remote worker.
                    raise RuntimeEnvError(
                        f"pip wheel not found: {r!r}")
                packed.append(package_file(r, kv))
            else:
                packed.append(r)
        out["pip"] = sorted(packed)
    out["_hash"] = env_hash(out)
    return out


def env_hash(spec: Optional[dict]) -> str:
    if not spec:
        return ""
    canon = {k: v for k, v in spec.items() if k != "_hash"}
    return hashlib.sha256(
        json.dumps(canon, sort_keys=True).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# Materialization (working_dir.py / py_modules.py parity)
# ---------------------------------------------------------------------------

class RuntimeEnvContext:
    """A materialized environment: what a worker needs at exec time."""

    def __init__(self, env_vars: Dict[str, str], cwd: Optional[str],
                 import_paths: List[str]):
        self.env_vars = env_vars
        self.cwd = cwd
        self.import_paths = import_paths

    def spawn_env(self, base: Optional[dict] = None) -> Dict[str, str]:
        """Env dict for a process-mode worker spawn."""
        env = dict(base if base is not None else os.environ)
        env.update(self.env_vars)
        if self.import_paths:
            extra = os.pathsep.join(self.import_paths)
            env["PYTHONPATH"] = extra + os.pathsep + env.get("PYTHONPATH", "")
        if self.cwd:
            env["RAY_TPU_WORKER_CWD"] = self.cwd
        return env


def _extract_uri(uri: str, kv, dest_root: str) -> str:
    import fcntl

    digest = uri[len("pkg://"):]
    dest = os.path.join(dest_root, digest)
    marker = os.path.join(dest, ".materialized")
    if os.path.exists(marker):
        return dest
    # Cross-process/thread exclusion: concurrent materializations of the
    # same package must not extract over files a finished caller is
    # already importing from.
    os.makedirs(dest_root, exist_ok=True)
    with open(os.path.join(dest_root, f".{digest}.lock"), "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):
                return dest
            blob = kv.get(_PKG_PREFIX + digest.encode())
            if blob is None:
                raise RuntimeEnvError(f"package {uri} not found in GCS KV")
            os.makedirs(dest, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(dest)
            open(marker, "w").close()
            return dest
        finally:
            fcntl.flock(lock_f, fcntl.LOCK_UN)


def _restore_wheel(uri: str, kv, dest_root: str) -> str:
    """pkg://digest/name.whl -> local wheel path under dest_root."""
    rest = uri[len("pkg://"):]
    digest, _, name = rest.partition("/")
    # Digest as a subdirectory: pip requires the wheel FILENAME intact.
    wheel_dir = os.path.join(dest_root, "wheels", digest)
    os.makedirs(wheel_dir, exist_ok=True)
    dest = os.path.join(wheel_dir, name)
    if not os.path.exists(dest):
        import uuid
        blob = kv.get(_PKG_PREFIX + digest.encode())
        if blob is None:
            raise RuntimeEnvError(f"wheel {uri} not found in GCS KV")
        # Unique tmp name: two pip specs sharing a wheel can restore
        # it concurrently (their flocks are keyed by DIFFERENT
        # req-hashes); os.replace makes the landing atomic either way.
        tmp = f"{dest}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, dest)
    return dest


def materialize_pip(requirements: List[str], kv,
                    dest_root: str) -> str:
    """Cached env dir per requirements-hash (reference pip.py: one
    virtual env per runtime_env, reused across tasks): pip-install the
    requirements into a private site dir (``--no-index`` when every
    requirement is a shipped wheel — this image has no egress) and
    return it for PYTHONPATH injection.

    Idempotent + cross-process locked like package extraction."""
    import fcntl
    import subprocess
    import sys

    req_hash = hashlib.sha256(
        json.dumps(sorted(requirements)).encode()).hexdigest()[:16]
    venv_root = os.path.join(dest_root, "venvs")
    venv_dir = os.path.join(venv_root, req_hash)
    marker = os.path.join(venv_dir, ".materialized")
    site = os.path.join(
        venv_dir, "lib",
        f"python{sys.version_info.major}.{sys.version_info.minor}",
        "site-packages")
    if os.path.exists(marker):
        return site
    os.makedirs(venv_root, exist_ok=True)
    with open(os.path.join(venv_root, f".{req_hash}.lock"), "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):
                return site
            local = [
                _restore_wheel(r, kv, dest_root)
                if r.startswith("pkg://") else r for r in requirements]
            all_wheels = all(r.endswith(".whl") for r in local)
            os.makedirs(site, exist_ok=True)
            # Install with THIS interpreter's pip targeted at the env's
            # own site dir (cheaper than a full `python -m venv` +
            # ensurepip bootstrap, identical import-path result).
            cmd = [sys.executable, "-m", "pip", "install", "--quiet",
                   "--target", site]
            if all_wheels:
                cmd += ["--no-index", "--no-deps"]
            cmd += local
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeEnvError(
                    f"pip install failed for runtime_env: "
                    f"{proc.stderr[-1500:]}")
            open(marker, "w").close()
            return site
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)


def materialize(spec: Optional[dict], kv,
                dest_root: Optional[str] = None) -> RuntimeEnvContext:
    """Download + extract the env's packages on this node; idempotent
    per content hash (uri_cache.py parity)."""
    if not spec:
        return RuntimeEnvContext({}, None, [])
    from ray_tpu._private.config import get_config
    dest_root = dest_root or os.path.join(get_config().temp_dir,
                                          "runtime_env")
    cwd = None
    import_paths: List[str] = []
    wd = spec.get("working_dir")
    if wd:
        cwd = _extract_uri(wd, kv, dest_root)
        import_paths.append(cwd)
    for uri in spec.get("py_modules") or []:
        import_paths.append(_extract_uri(uri, kv, dest_root))
    if spec.get("pip"):
        import_paths.append(
            materialize_pip(list(spec["pip"]), kv, dest_root))
    return RuntimeEnvContext(dict(spec.get("env_vars") or {}), cwd,
                             import_paths)


# ---------------------------------------------------------------------------
# Thread-mode application (approximation; process mode is the real path)
# ---------------------------------------------------------------------------

_env_lock = diag_lock("runtime_env._env_lock")


@contextlib.contextmanager
def applied(ctx: RuntimeEnvContext):
    """Apply env vars (global, locked) and import paths around a task
    body in a thread-mode worker."""
    import sys
    if not ctx.env_vars and not ctx.import_paths:
        yield
        return
    with _env_lock:
        saved = {k: os.environ.get(k) for k in ctx.env_vars}
        os.environ.update(ctx.env_vars)
        added = [p for p in ctx.import_paths if p not in sys.path]
        sys.path[:0] = added
        try:
            yield
        finally:
            for p in added:
                try:
                    sys.path.remove(p)
                except ValueError:
                    pass
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
