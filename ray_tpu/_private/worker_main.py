"""Standalone worker process: ``python -m ray_tpu._private.worker_main``.

Parity: reference worker processes started by the raylet's pool
(``src/ray/raylet/worker_pool.h:428`` StartWorkerProcess spawns
``python/ray/_private/workers/default_worker.py``, which registers back
over the raylet socket and then serves ``CoreWorkerService.PushTask``,
``core_worker.proto:353``).

Protocol here (framed RPC, ray_tpu.rpc):
  1. start an RpcServer on an ephemeral port serving push/stop;
  2. connect to the raylet host service and ``register_worker`` with
     (worker_id, port) — the handshake the pool's ProcessWorker waits on;
  3. each ``push`` request executes one task: args arrive inline
     (serialized blobs) or as object ids fetched from the raylet host via
     ``get_object``; function blobs are fetched from the GCS KV via
     ``kv_get`` and cached; serialized return values ride back in the
     reply (the host stores them with owner semantics).

Task bodies get the FULL public API: after registration the process's
global worker is wired to the host via ``client_runtime`` (the
reference's in-worker CoreWorker role), so nested ``.remote`` calls,
``put/get/wait``, actor creation/lookup/kill all work from inside a
process-mode task.
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys
import threading
import traceback
from typing import Dict, Optional

from ray_tpu import exceptions
from ray_tpu._private.debug.lock_order import diag_lock
from ray_tpu._private.serialization import (
    SerializedObject, deserialize, loads_function, serialize,
    serialize_into)
from ray_tpu.rpc import RpcClient, RpcServer

_SHM_MISS = object()
# Returns below this ride the reply socket (the owner memory-store
# inline path wants them anyway); above it they go through the segment.
_SHM_RETURN_MIN = 100 * 1024


class _ShmReturnWriter:
    """serialize_into writer for the write-through-shm return path
    (plasma Create/Seal): ``reserve`` asks the host for a segment
    block, the serializer fills it through this process's mapping (the
    single data copy — no intermediate flattened bytes), ``commit``
    seals it host-side.  Declines small values (they ride the reply
    socket, which the owner memory-store inline path wants anyway) and
    cleans up its own reservation on any failure, so a False outcome
    simply means "use the socket fallback"."""

    __slots__ = ("_runtime", "_oid_bin", "_off")

    def __init__(self, runtime: "_WorkerRuntime", oid_bin: bytes):
        self._runtime = runtime
        self._oid_bin = oid_bin
        self._off = None

    def reserve(self, nbytes: int):
        shm = self._runtime._shm
        if shm is None or nbytes <= _SHM_RETURN_MIN:
            return None
        try:
            off = self._runtime.node_client.call(
                "shm_create", {"object_id": self._oid_bin,
                               "size": nbytes}, timeout=30.0)
        except Exception:
            return None
        if off is None:
            return None
        self._off = int(off)
        return shm.view(self._off, nbytes)

    def commit(self, _serialized, nbytes: int) -> bool:
        try:
            if self._runtime.node_client.call(
                    "shm_seal", {"object_id": self._oid_bin,
                                 "size": nbytes}, timeout=30.0):
                return True
        except Exception:
            pass
        self._abort_reservation()
        return False

    def abort(self, _exc) -> None:
        self._abort_reservation()

    def _abort_reservation(self) -> None:
        # The write/seal failed mid-way: the reservation is invisible
        # to eviction — abort it host-side or it leaks.
        if self._off is None:
            return
        try:
            self._runtime.node_client.call_async(
                "shm_abort", {"object_id": self._oid_bin},
                lambda _r, _e: None)
        except Exception:
            pass


class _CtxSpec:
    """Task-context slice for runtime_context inside the child (the host
    ships the relevant spec fields in the push payload)."""

    def __init__(self, payload):
        from ray_tpu.scheduler.resources import ResourceRequest
        self.task_id = payload.get("task_id")
        self.actor_id = payload.get("actor_id")
        self.task_type = payload.get("task_type", "NORMAL_TASK")
        self.resources = ResourceRequest(payload.get("resources") or {})
        lr = payload.get("lifetime_resources")
        self.lifetime_resources = \
            ResourceRequest(lr) if lr is not None else None
        self.depth = 0
        self.function_name = payload.get("function_name", "")
        self.placement_group_id = payload.get("placement_group_id")
        self.placement_group_bundle_index = \
            payload.get("placement_group_bundle_index", -1)

    def is_actor_creation(self) -> bool:
        return self.task_type == "ACTOR_CREATION_TASK"

    def is_actor_task(self) -> bool:
        return self.task_type == "ACTOR_TASK"


class _WorkerRuntime:
    def __init__(self, host: str, port: int, worker_id: str):
        self.worker_id = worker_id
        self.node_client = RpcClient((host, port))
        self.server = RpcServer(name=f"worker-{worker_id[:8]}")
        self.server.register_async("push", self._handle_push)
        self.server.register("ping", lambda _p: "pong")
        self.server.register("stop", self._handle_stop)
        self._fn_cache: Dict[bytes, object] = {}
        self.actor_instance = None
        self._sema: Optional[threading.Semaphore] = None
        # Per-concurrency-group bounds (concurrency_group_manager.cc).
        self._group_semas: Dict[str, threading.Semaphore] = {}
        self._order_lock = diag_lock("WorkerServer._order_lock")
        self._stop_event = threading.Event()
        # Plasma-client mapping of the node's shm segment (metadata via
        # node_client RPC, bytes through this mmap — zero-copy).
        self._shm = None

    def _attach_shm(self):
        try:
            info = self.node_client.call("shm_info", None, timeout=10.0)
            if info:
                from ray_tpu.native.shm_store import AttachedSegment
                self._shm = AttachedSegment(info["name"],
                                            info["capacity"])
        except Exception:
            self._shm = None

    def run(self):
        # Nested-.remote support: wire this process's global worker to
        # the host BEFORE registering — a task can be pushed the moment
        # registration lands (client_runtime — the reference's in-worker
        # CoreWorker role).
        from ray_tpu._private import client_runtime
        client_runtime.install(self.node_client,
                               client_worker_id=self.worker_id)
        # Attach the segment BEFORE registering: a task can be pushed
        # the moment registration lands, and it must find the mapping.
        self._attach_shm()
        self.node_client.call("register_worker", {
            "worker_id": self.worker_id,
            "port": self.server.address[1],
            "pid": os.getpid(),
        })

        # Orphan watchdog: if the host process dies without a clean
        # "stop", exit rather than linger (reference: workers die with
        # their raylet).
        def watchdog():
            while not self._stop_event.is_set():
                try:
                    self.node_client.call("ping", None, timeout=10.0)
                except Exception:
                    self._stop_event.set()
                    return
                self._stop_event.wait(timeout=5.0)

        threading.Thread(target=watchdog, daemon=True,
                         name="ray_tpu::worker::watchdog").start()
        self._stop_event.wait()
        self.server.stop()

    # ---- execution -----------------------------------------------------
    def _handle_stop(self, _payload):
        self._stop_event.set()
        return True

    def _handle_push(self, payload, reply):
        kind = payload["kind"]
        sema = None
        if kind == "actor_task":
            group = payload.get("concurrency_group") or ""
            sema = self._group_semas.get(group, self._sema)
        if sema is not None:
            sema.acquire()
            try:
                reply(self._execute(payload))
            finally:
                sema.release()
        else:
            reply(self._execute(payload))

    def _execute(self, payload) -> dict:
        from ray_tpu._private import worker_context
        from ray_tpu.util import tracing
        prev_ctx = worker_context.get_context()
        worker_context.set_context(worker_context.ExecutionContext(
            task_spec=_CtxSpec(payload), node=None, worker=None))
        trace_ctx = payload.get("trace_ctx")
        pinned: list = []
        out: dict
        try:
            with tracing.span(
                    f"execute:{payload.get('function_name', '?')}",
                    category="execute", parent=trace_ctx,
                    force=bool(trace_ctx)):
                kind = payload["kind"]
                # Actor calls (and creation) copy shm args out of the
                # mapping so their pins can be released at frame end —
                # an arg kept as actor state must not reference a
                # region the host could evict once unpinned.  Normal
                # tasks stay zero-copy (args die with the frame).
                args, kwargs = self._resolve_args(
                    payload["args"], pinned, copy_shm=(kind != "task"))
                if kind == "create_actor":
                    cls = self._load_function(payload["function_key"])
                    self.actor_instance = cls(*args, **kwargs)
                    n = max(1, int(payload.get("max_concurrency", 1)))
                    self._sema = threading.Semaphore(n)
                    for gname, gsize in (
                            payload.get("concurrency_groups")
                            or {}).items():
                        self._group_semas[gname] = threading.Semaphore(
                            max(1, int(gsize)))
                    out = {"error": None, "returns": []}
                elif kind == "actor_task":
                    if self.actor_instance is None:
                        raise exceptions.RayTpuError(
                            "actor not initialized")
                    method = getattr(self.actor_instance,
                                     payload["actor_method_name"])
                    result = method(*args, **kwargs)
                    out = {"error": None,
                           "returns": self._pack_returns(payload, result)}
                else:
                    fn = self._load_function(payload["function_key"])
                    result = fn(*args, **kwargs)
                    out = {"error": None,
                           "returns": self._pack_returns(payload, result)}
        except Exception as e:  # noqa: BLE001 — user errors cross the wire
            err = exceptions.TaskError(
                e, task_desc=f"{payload.get('function_name', '?')}"
                             f"[process-worker]")
            try:
                blob = pickle.dumps(err)
            except Exception:
                blob = pickle.dumps(exceptions.RayTpuError(
                    "".join(traceback.format_exception(e))))
            out = {"error": blob, "returns": []}
        finally:
            worker_context.set_context(prev_ctx)
            # Every kind releases its pins at frame end: normal-task
            # args died with the frame (zero-copy views included), and
            # actor creation/call args were copied out of the mapping
            # above.  Holding pins for an actor's lifetime permanently
            # pinned every large shm arg a long-lived actor ever took
            # (ADVICE.md).
            if pinned:
                self._release_pins(pinned)
        if trace_ctx:
            # Ship locally-recorded spans back on the reply (ProfileEvent
            # batching parity) — the driver's pool ingests them.
            out["trace"] = tracing.drain()
        return out

    def _resolve_args(self, packed, pinned, copy_shm: bool = False):
        from ray_tpu._private.executor import _split_args
        flat = []
        for kind, data in packed:
            if kind == "inline":
                flat.append(deserialize(SerializedObject.from_bytes(data)))
                continue
            value = self._shm_get(data, pinned, copy=copy_shm)
            if value is not _SHM_MISS:
                flat.append(value)
                continue
            blob = self.node_client.call("get_object", data, timeout=30.0)
            if blob is None:
                raise exceptions.ObjectLostError(
                    data.hex(), "arg not available on host node")
            flat.append(deserialize(SerializedObject.from_bytes(blob)))
        return _split_args(flat)

    def _shm_get(self, oid_bin: bytes, pinned: list, copy: bool = False):
        """Arg read through the segment (plasma client Get): locate
        pins the object host-side, bytes come from the read-only
        mapping.  ``copy=False`` (normal tasks) keeps zero-copy — the
        deserialized arrays reference the mapping and the pin holds
        until task end.  ``copy=True`` (actor creation/calls) snapshots
        the bytes first so the value survives the pin release at frame
        end.  Every pin key lands in ``pinned``."""
        if self._shm is None:
            return _SHM_MISS
        try:
            loc = self.node_client.call(
                "shm_locate", {"object_id": oid_bin,
                               "worker_id": self.worker_id},
                timeout=30.0)
        except Exception:
            return _SHM_MISS
        if loc is None:
            return _SHM_MISS
        pinned.append(oid_bin)
        view = self._shm.read(int(loc[0]), int(loc[1]))
        if copy:
            view = bytes(view)
        return deserialize(SerializedObject.from_bytes(view))

    def _release_pins(self, pinned: list):
        for oid_bin in pinned:
            try:
                self.node_client.call_async(
                    "shm_release", {"object_id": oid_bin,
                                    "worker_id": self.worker_id},
                    lambda _r, _e: None)
            except Exception:
                pass

    def _pack_returns(self, payload, result):
        num = payload["num_returns"]
        if num == 0:
            return []
        values = [result] if num == 1 else list(result)
        if len(values) != num:
            raise ValueError(
                f"task returned {len(values)} values, expected {num}")
        out = []
        for oid_bin, value in zip(payload["return_ids"], values):
            # Single-copy return: serialize straight into the mapped
            # segment when the host grants a reservation (sealed
            # host-side, nothing crosses the socket); otherwise the
            # SAME SerializedObject rides the reply socket flattened.
            serialized, in_shm = serialize_into(
                value, _ShmReturnWriter(self, oid_bin))
            out.append((oid_bin, None if in_shm
                        else serialized.to_bytes()))
        return out

    def _load_function(self, key: bytes):
        fn = self._fn_cache.get(key)
        if fn is None:
            blob = self.node_client.call("kv_get", key, timeout=30.0)
            if blob is None:
                raise KeyError(f"function blob missing for {key!r}")
            fn = loads_function(blob)
            self._fn_cache[key] = fn
        return fn


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--worker-id", required=True)
    args = parser.parse_args(argv)
    # Runtime-env working_dir: the spawner materialized it and points us
    # at it (reference: worker started inside its env's directory).
    cwd = os.environ.get("RAY_TPU_WORKER_CWD")
    if cwd:
        os.chdir(cwd)
        sys.path.insert(0, cwd)
    if os.environ.get("RAY_TPU_TRACING") == "1":
        from ray_tpu.util import tracing
        tracing.enable()
    runtime = _WorkerRuntime(args.host, args.port, args.worker_id)
    runtime.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
