"""Head-side wire service: the process boundary in front of the cluster.

Parity: reference ``src/ray/gcs/gcs_server/gcs_server.h:182-237`` — the
head's service surface (NodeInfoGcsService RegisterNode/UnregisterNode,
heartbeats, InternalKV, the object directory that owners answer location
queries from) — plus the head half of the lease protocol
(``node_manager.proto:300-357``): the GCS and driver-side submitters talk
to a remote raylet exactly as they talk to an in-process one, through a
``RemoteNodeProxy`` that forwards every Raylet surface over the node's
framed-RPC connection.

Control plane is hub-and-spoke (every node registers with and
heartbeats this one server); the OBJECT plane is peer-to-peer: the
directory answers location queries with dialable node addresses and
peers pull chunked bytes directly from each other
(``object_manager.proto:61`` ObjectManagerService parity).  The head
relays object bytes only for ray-client drivers, whose sole connection
is the head.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ray_tpu import exceptions
from ray_tpu._private.ids import NodeID, ObjectID, WorkerID
from ray_tpu._private.serialization import SerializedObject
from ray_tpu.rpc import RpcClient, RpcServer
from ray_tpu.scheduler.resources import NodeResources
from ray_tpu._private.debug import diag_lock, flight_recorder


def _ignore(_result, _err):
    pass


def _head_clock(_payload) -> float:
    """The cluster's reference wall-clock (clock_probe RPC)."""
    import time
    return time.time()


class _RemoteWorkerHandle:
    """Head-side stand-in for a leased worker living in a NodeHost
    process.  Duck-types the thread ``Worker`` surface the submitters and
    the GCS actor manager use: push_task / assign_actor /
    submit_actor_task / kill_actor, each forwarded over the node's wire
    with the lease token (CoreWorkerService.PushTask parity — the raylet
    is off the data path, but hub-and-spoke v1 routes through the node's
    host server rather than a per-worker port)."""

    def __init__(self, proxy: "RemoteNodeProxy", token: bytes):
        self.worker_id = WorkerID(token)
        self.node_id = proxy.node_id
        self.state = "LEASED"
        self._proxy = proxy

    def _push(self, method: str, spec, on_done):
        import pickle

        def on_reply(result, err):
            if err is not None:
                on_done(exceptions.WorkerCrashedError(
                    f"worker host connection lost: {err}"))
                return
            if result.get("trace"):
                from ray_tpu.util import tracing
                tracing.ingest(result["trace"])
            blob = result.get("error")
            if blob is None:
                on_done(None)
                return
            try:
                on_done(pickle.loads(blob))
            except Exception:
                on_done(exceptions.RayTpuError("undecodable worker error"))

        self._proxy.client.call_async(
            method, {"worker_token": self.worker_id.binary(), "spec": spec},
            on_reply)

    def push_task(self, spec, on_done):
        self._push("push_task", spec, on_done)

    def assign_actor(self, creation_spec, on_done):
        def wrap(err):
            if err is None:
                self.state = "ACTOR"
            on_done(err)

        self._push("assign_actor", creation_spec, wrap)

    def submit_actor_task(self, spec, on_done):
        self._push("push_actor_task", spec, on_done)

    def kill_actor(self):
        # Route through the proxy so the lease token leaves
        # _held_tokens — a direct return_worker send would leak it into
        # every future reconcile payload.
        self._proxy.return_worker(self, disconnect=True)

    def stop(self):
        self.kill_actor()


class _ProxyObjectStore:
    """The sliver of NodeObjectStore the head touches on a remote node:
    serialized reads for pulls, deletes for the free path.  ``get``
    returns None — entry metadata (size) stays node-local, so the
    locality lease policy falls back to presence-in-directory, which is
    the signal that matters."""

    def __init__(self, proxy: "RemoteNodeProxy"):
        self._proxy = proxy

    def get(self, object_id: ObjectID):
        return None

    def contains(self, object_id: ObjectID) -> bool:
        return False

    def get_serialized(self, object_id: ObjectID
                       ) -> Optional[SerializedObject]:
        from ray_tpu.rpc.chunked import fetch_chunked
        try:
            blob = fetch_chunked(self._proxy.client, object_id.binary(),
                                 timeout=300.0)
        except Exception:
            return None
        return None if blob is None else SerializedObject.from_bytes(blob)

    def fetch_into(self, object_id: ObjectID, local_store,
                   pipeline: int = 8, on_chunk=None,
                   timeout: float = 300.0,
                   busy_patience_s: Optional[float] = None):
        """Streamed head-side pull from a spoke: the windowed chunk
        pipeline assembles directly into a reserved block of the head's
        segment (same zero-copy receive path the spokes use)."""
        from ray_tpu._private.object_manager import fetch_object_into
        try:
            return fetch_object_into(
                self._proxy.client, object_id, local_store,
                pipeline=pipeline, on_chunk=on_chunk, timeout=timeout,
                busy_patience_s=busy_patience_s)
        except Exception:
            return None

    def delete(self, object_id: ObjectID):
        self._proxy.client.call_async(
            "delete_object", {"object_id": object_id.binary()}, _ignore)


def _merge_broadcast(pending: Optional[dict], batch: dict) -> dict:
    """Fold a new resource broadcast into the batch already waiting
    behind an in-flight send.  A FULL batch supersedes pending rows
    wholesale; a delta layered on anything keeps the older coverage
    (full stays full) with the newer rows winning.  Removals union —
    a removal is an event, not a state — and the suspect set is pure
    state, so latest wins."""
    if pending is None:
        return batch
    if batch.get("full"):
        rows, full = dict(batch["rows"]), True
    else:
        rows = dict(pending["rows"])
        rows.update(batch["rows"])
        full = bool(pending.get("full"))
    removed = list(dict.fromkeys(
        list(pending.get("removed") or []) +
        list(batch.get("removed") or [])))
    return {"rows": rows, "full": full, "removed": removed,
            "suspect": list(batch.get("suspect") or [])}


class RemoteNodeProxy:
    """Duck-types ``Raylet`` on the head for one NodeHost process.

    Every surface the GCS (register/poll/broadcast/PG-2PC), the driver
    submitters (lease/return), and the object plane (serialized reads,
    deletes) call on an in-process Raylet is forwarded over the node's
    RpcClient; neither side's runtime code knows the wire exists."""

    def __init__(self, node_id: NodeID, node_name: str,
                 resources: Dict[str, float], labels: Dict,
                 address):
        self.node_id = node_id
        self.node_name = node_name
        self.local_resources = NodeResources(resources, labels=labels)
        self.address = tuple(address)    # peers dial this directly
        self.client = RpcClient(tuple(address))
        self.object_store = _ProxyObjectStore(self)
        self.is_remote_proxy = True
        #: Minted by GcsNodeManager.register_node when this proxy is
        #: adopted (incarnation fencing); returned to the node in its
        #: registration reply.
        self.incarnation = None
        #: Set by the head when this proxy's node is declared dead /
        #: superseded: a LATE lease reply arriving afterwards must not
        #: wrap a worker handle — the zombie's grant is rejected and
        #: counted as a fenced lease reply.
        self.fenced = False
        #: Callable(verb) the head installs to count fenced rejections
        #: against the GCS node manager.
        self.fence_notify = None
        self._last_report = {
            "available": dict(resources),
            "total": dict(resources),
            "load": {"queued": 0, "dispatch": 0},
        }
        # Lease tokens this head currently holds on the node.  After a
        # connection drop, a lease the node granted whose reply died
        # with the old connection is held by NOBODY — on reconnect the
        # head sends its held set and the node releases the rest
        # (reference ReleaseUnusedWorkers, node_manager.proto:312).
        self._held_tokens: set = set()
        self._tokens_lock = diag_lock("RemoteNodeProxy._tokens_lock")
        # Resource-broadcast coalescing (64-node fan-out fix): at most
        # ONE update_resource_usage RPC in flight per node; broadcasts
        # arriving behind a slow send merge into a single pending batch
        # instead of queueing unbounded RPCs on the node's wire.
        self._bcast_lock = diag_lock("RemoteNodeProxy._bcast_lock")
        self._bcast_inflight = False
        self._bcast_pending: Optional[dict] = None
        self.broadcasts_coalesced = 0
        self.broadcasts_sent = 0
        self.client.on_reconnect = self._reconcile_leases
        # Periodic reconcile, not just on-reconnect: a lease the
        # client's bounded retry loop gave up on (the node's grant
        # landed after rpc_retry_attempts x lease_rpc_timeout_s) is
        # held by NOBODY while the connection stayed up — without a
        # clock-driven sweep that worker slot leaks until some
        # unrelated reconnect happens.
        self._stopped = False
        self._reconcile_timer = None
        self._schedule_periodic_reconcile()

    # ---- GCS-facing (register / resource sync) -------------------------
    def node_info(self) -> dict:
        return {
            "node_id": self.node_id.hex(),
            "node_name": self.node_name,
            "alive": True,
            "remote": True,
            "resources": self.local_resources.to_float_dict("total"),
            "labels": dict(self.local_resources.labels),
        }

    def get_resource_report(self) -> dict:
        """Non-blocking: return the last report and refresh it
        asynchronously — the GCS poll loop must never block on a peer's
        wire (ray_syncer polls on a dedicated thread for the same
        reason)."""

        def on_reply(result, err):
            if err is None and isinstance(result, dict):
                self._last_report = result

        self.client.call_async("get_resource_report", None, on_reply)
        return self._last_report

    def update_resource_usage(self, batch: dict):
        """Coalescing broadcast send: at most one RPC in flight.  A
        batch arriving while a send is outstanding MERGES into the
        pending batch (newest rows win, removals/suspects union/latest)
        rather than stacking another async RPC behind a slow node —
        under 64-node fan-out with one congested spoke the old path
        accumulated unbounded in-flight broadcasts on that spoke's
        wire while every healthy node waited on the same client."""
        with self._bcast_lock:
            if self._bcast_inflight:
                self._bcast_pending = _merge_broadcast(
                    self._bcast_pending, batch)
                self.broadcasts_coalesced += 1
                return
            self._bcast_inflight = True
        self._send_broadcast(batch)

    def _send_broadcast(self, batch: dict):
        def on_done(_result, _err):
            # Errors are already swallowed by the async client path
            # (same contract as the old fire-and-forget); what matters
            # here is draining the pending batch exactly once.
            with self._bcast_lock:
                pending, self._bcast_pending = self._bcast_pending, None
                if pending is None:
                    self._bcast_inflight = False
                    return
            self._send_broadcast(pending)

        self.broadcasts_sent += 1
        try:
            self.client.call_async("update_resource_usage", batch, on_done)
        except Exception:
            with self._bcast_lock:
                self._bcast_inflight = False
                self._bcast_pending = None
            raise

    # ---- lease protocol ------------------------------------------------
    def _fence_grant(self, result: dict, token) -> bool:
        """A lease reply landing AFTER this proxy was fenced (node
        declared dead, or superseded by a newer incarnation) must not
        produce a usable worker handle: the zombie's grant is converted
        to a rejection and counted — the lease-reply resurrection
        vector of the fencing acceptance."""
        if not self.fenced:
            return False
        if token is not None and self.fence_notify is not None:
            try:
                self.fence_notify("lease_reply")
            except Exception:
                pass
        result.clear()
        result.update({"rejected": True,
                       "reason": "node fenced (stale incarnation)"})
        return True

    def request_worker_lease(self, spec, reply):
        def on_reply(result, err):
            if err is not None:
                reply({"rejected": True,
                       "reason": f"node connection lost: {err}"})
                return
            token = result.pop("worker_token", None)
            if self._fence_grant(result, token):
                reply(result)
                return
            if token is not None:
                with self._tokens_lock:
                    self._held_tokens.add(token)
                result["worker"] = _RemoteWorkerHandle(self, token)
                result["raylet"] = self
            reply(result)

        from ray_tpu._private.config import get_config
        self.client.call_async("request_worker_lease", spec, on_reply,
                               timeout=get_config().lease_rpc_timeout_s)

    def request_worker_lease_batch(self, specs, reply):
        """Batched lease protocol over the wire: N same-class lease
        entries in ONE RPC; the reply vector's grant tokens are wrapped
        into remote worker handles exactly like the single path.  A
        connection error rejects every entry (the submitter's transient
        re-lease machinery takes over)."""

        def on_reply(result, err):
            if err is not None:
                reply({"results": [
                    {"rejected": True,
                     "reason": f"node connection lost: {err}"}
                    for _ in specs]})
                return
            results = (result or {}).get("results") or []
            for r in results:
                token = r.pop("worker_token", None)
                if self._fence_grant(r, token):
                    continue
                if token is not None:
                    with self._tokens_lock:
                        self._held_tokens.add(token)
                    r["worker"] = _RemoteWorkerHandle(self, token)
                    r["raylet"] = self
            reply({"results": results})

        from ray_tpu._private.config import get_config
        self.client.call_async("request_worker_lease_batch",
                               {"specs": specs}, on_reply,
                               timeout=get_config().lease_rpc_timeout_s)

    def return_worker(self, worker, disconnect: bool = False):
        token = worker.worker_id.binary()
        # Mirror the node's own bookkeeping: a dedicated actor worker's
        # token stays live across non-disconnect returns.
        if disconnect or getattr(worker, "state", "") != "ACTOR":
            with self._tokens_lock:
                self._held_tokens.discard(token)
        from ray_tpu._private.config import get_config
        self.client.call_async(
            "return_worker",
            {"worker_token": token, "disconnect": disconnect},
            _ignore, timeout=get_config().lease_rpc_timeout_s)

    def _reconcile_leases(self):
        """on_reconnect hook: tell the node which lease tokens this head
        still holds so it can release grants whose replies were lost
        with the previous connection.

        The node exempts grants younger than its grace window (their
        reply may be in flight on the new connection) — but the lost
        grant this hook exists for is usually itself younger than the
        window at reconnect time, so one sweep is not enough: schedule
        a follow-up after the window has passed, when every genuinely
        leaked token has aged into sweepable range."""
        self._send_reconcile()
        from ray_tpu._private.config import get_config
        delay = get_config().lease_reconcile_grace_s * 1.5 + 0.1
        timer = threading.Timer(delay, self._send_reconcile)
        timer.daemon = True
        timer.start()

    def _schedule_periodic_reconcile(self):
        from ray_tpu._private.config import get_config
        if self._stopped or self.fenced:
            return
        period = max(5.0, get_config().lease_reconcile_grace_s * 3.0)
        timer = threading.Timer(period, self._periodic_reconcile)
        timer.daemon = True
        self._reconcile_timer = timer
        timer.start()

    def _periodic_reconcile(self):
        if self._stopped or self.fenced:
            return
        self._send_reconcile()
        self._schedule_periodic_reconcile()

    def _stop_reconcile(self):
        self._stopped = True
        timer = self._reconcile_timer
        if timer is not None:
            timer.cancel()

    def _send_reconcile(self):
        with self._tokens_lock:
            held = list(self._held_tokens)
        try:
            self.client.call("reconcile_leases", {"held": held},
                             timeout=30.0)
        except Exception:
            pass   # the periodic sweep retries

    # ---- placement-group 2PC (node_manager.proto:319-330) --------------
    def prepare_bundle_resources(self, pg_id, idx: int, req) -> bool:
        try:
            return bool(self.client.call(
                "prepare_bundle",
                {"pg_id": pg_id, "index": idx, "request": req},
                timeout=30.0))
        except Exception:
            return False

    def commit_bundle_resources(self, pg_id, idx: int, req):
        self.client.call(
            "commit_bundle",
            {"pg_id": pg_id, "index": idx, "request": req}, timeout=30.0)

    def cancel_resource_reserve(self, pg_id, idx: int):
        self.client.call_async(
            "cancel_bundle", {"pg_id": pg_id, "index": idx}, _ignore)

    # ---- lifecycle -----------------------------------------------------
    def shutdown(self):
        self._stop_reconcile()
        try:
            self.client.call("stop", None, timeout=5.0)
        except Exception:
            pass
        self.client.close()

    def kill(self):
        """Head-side bookkeeping only — hard node death is the process
        dying; heartbeat timeout does the declaring."""
        self._stop_reconcile()
        self.client.close()

    def debug_string(self) -> str:
        return f"RemoteNodeProxy {self.node_name} ({self.node_id.hex()[:8]})"


class HeadService:
    """RPC server on the head exposing the GCS + owner surfaces that
    ``node_host.py`` forwards to: registration, heartbeats, KV reads,
    the object directory, inline return delivery, and hub-relayed object
    fetches."""

    def __init__(self, cluster, port: int = 0):
        from ray_tpu._private.metrics_agent import MetricsFederation
        self._cluster = cluster
        self._lock = diag_lock("HeadService._lock")
        self._proxies: Dict[NodeID, RemoteNodeProxy] = {}
        self._reg_tokens: Dict[str, NodeID] = {}
        # Object bytes relayed head-through for a peer that could have
        # pulled directly.  The peer-to-peer plane keeps this at zero in
        # steady state; tests assert on it.
        self.relay_fetches = 0
        # Registration admission (fan-in backpressure): count of
        # register_node handlers currently running; over the config cap
        # the handler replies busy instead of dialing a proxy, so a
        # 64-host storm ramps in instead of piling 64 simultaneous
        # connection setups + adoptions onto the dispatch pool.
        self._registrations_active = 0
        self.registrations_deferred = 0
        # Cluster-wide /metrics: every node_host's shipped registry
        # delta merges here under a node_id label; a dead node's series
        # are pruned with its federation entry.
        self.metrics_federation = MetricsFederation()
        # Internal-loop liveness per node (the "why is it stuck" plane):
        # node hosts ship wedge reports as their watchdog fires — a node
        # whose raylet loop is wedged still HEARTBEATS, so this map sees
        # what the heartbeat plane cannot.  node_hex -> state dict.
        self.loop_liveness: Dict[str, dict] = {}
        self.server = RpcServer(port=port, name="head")
        s = self.server
        s.register("register_node", self._handle_register_node)
        s.register("unregister_node", self._handle_unregister_node)
        s.register("heartbeat", self._handle_heartbeat)
        s.register("metrics_report", self._handle_metrics_report)
        s.register("wedge_report", self._handle_wedge_report)
        s.register("debug_dump", self._handle_debug_dump)
        # Clock-sync anchor: nodes probe this to estimate their offset
        # to the head clock (timeline normalization, stage durations).
        s.register("clock_probe", _head_clock)
        s.register("actor_worker_died", self._handle_actor_worker_died)
        s.register("kv_get", self._handle_kv_get)
        s.register("fetch_object", self._handle_fetch_object)
        s.register("fetch_value", self._handle_fetch_value)
        s.register("put_inline", self._handle_put_inline)
        s.register("add_location", self._handle_add_location)
        s.register("remove_location", self._handle_remove_location)
        s.register("add_partial_location",
                   self._handle_add_partial_location)
        s.register("remove_partial_location",
                   self._handle_remove_partial_location)
        s.register("get_locations", self._handle_get_locations)
        s.register("get_node_address", self._handle_get_node_address)
        s.register_async("wait_object", self._handle_wait_object)
        s.register("ping", lambda _p: "pong")
        # Long-poll batched pubsub (src/ray/pubsub parity): remote
        # subscribers long-poll one mailbox each; remote publishers
        # (worker-log streams from spokes) arrive as batches.
        from ray_tpu.gcs.wire_pubsub import WirePubsubService
        self.pubsub_service = WirePubsubService(cluster.gcs.publisher, s)
        # Chunked object plane (pull_manager/push_manager parity): any
        # object size crosses the wire as chunk frames with per-chunk
        # acks and sender-side admission control.
        from ray_tpu._private.object_store import segment_chunk_source
        from ray_tpu.rpc.chunked import serve_chunks

        def _head_segment_source(oid_bin):
            head = cluster.head_node
            if head is None:
                return None
            return segment_chunk_source(head.object_store)(oid_bin)

        def _head_partial_source(oid_bin):
            from ray_tpu._private.object_store import partial_chunk_source
            head = cluster.head_node
            if head is None:
                return None
            return partial_chunk_source(head.object_store)(oid_bin)

        head_store = cluster.head_node.object_store \
            if cluster.head_node is not None else None
        self.chunk_server = serve_chunks(
            s, lambda oid_bin: self._handle_fetch_object(
                {"object_id": oid_bin}),
            get_source=_head_segment_source,
            get_partial=_head_partial_source,
            ledger=head_store.transfer_ledger
            if head_store is not None else None)
        # Remote-driver surface (Ray Client parity): drivers in other
        # processes connect via init(address="ray-tpu://host:port").
        from ray_tpu._private.client_service import register_client_surface
        from ray_tpu._private.worker import global_worker_or_none

        def _namespace():
            w = global_worker_or_none()
            return getattr(w, "namespace", "") if w else ""

        register_client_surface(
            s,
            core=lambda: self._require_core(),
            kv=cluster.gcs.kv,
            actor_manager=lambda: self._cluster.gcs.actor_manager,
            node_id_fn=lambda: (cluster.head_node.node_id
                                if cluster.head_node else None),
            namespace_fn=_namespace,
            chunk_server=self.chunk_server)
        cluster.gcs.subscribe_node_death(self._on_node_death)

    @property
    def address(self):
        return self.server.address

    def _require_core(self):
        core = self._cluster.core_worker
        if core is None:
            raise RuntimeError("head has no core worker attached")
        return core

    # ---- membership ----------------------------------------------------
    def _fence_gate(self, payload, verb: str) -> Optional[dict]:
        """Incarnation fencing admission check for node-originated wire
        messages.  None = admitted.  A payload stamped with a
        non-current ``(node_id, incarnation)`` — a zombie's heartbeat,
        metrics report, location row, wedge report, inline return — is
        rejected with ``{"fenced": True, ...}``; the sender drains and
        re-registers when it sees it.  Payloads WITHOUT an incarnation
        stamp pass (driver-side/internal senders are not node-bound)."""
        if not isinstance(payload, dict):
            return None
        inc = payload.get("incarnation")
        if inc is None or "node_id" not in payload:
            return None
        node_id = NodeID(payload["node_id"])
        nm = self._cluster.gcs.node_manager
        if nm.check_incarnation(node_id, inc):
            return None
        nm.note_fenced(node_id, verb)
        return {"fenced": True, "rejected": int(inc),
                "incarnation": nm.current_incarnation(node_id)}

    def _handle_register_node(self, payload):
        from ray_tpu._private.config import get_config
        cap = get_config().head_registration_concurrency
        with self._lock:
            if cap > 0 and self._registrations_active >= cap:
                self.registrations_deferred += 1
                # Spread retries: base backoff plus a slot proportional
                # to how deep the deferral queue is right now.
                retry_ms = 50 + 25 * min(
                    self.registrations_deferred % 32, 31)
                return {"busy": True, "retry_after_ms": retry_ms}
            self._registrations_active += 1
        try:
            return self._admit_register_node(payload)
        finally:
            with self._lock:
                self._registrations_active -= 1

    def _admit_register_node(self, payload):
        node_id = NodeID(payload["node_id"])
        proxy = RemoteNodeProxy(
            node_id, payload.get("node_name", ""),
            payload["resources"], payload.get("labels") or {},
            (payload.get("host", "127.0.0.1"), payload["port"]))
        proxy.fence_notify = \
            lambda verb, _nid=node_id: \
            self._cluster.gcs.node_manager.note_fenced(_nid, verb)
        with self._lock:
            old = self._proxies.get(node_id)
            self._proxies[node_id] = proxy
            token = payload.get("reg_token")
            if token:
                self._reg_tokens[token] = node_id
        if old is not None:
            # Re-registration while the prior proxy still exists (a
            # fenced node coming back before/without a death prune):
            # the old mirror is superseded — fence its late replies and
            # tear its connection down.
            old.fenced = True
            old.client.close()
        # A re-registering node id must be able to federate metrics
        # again: lift the death-prune tombstone for it (the incarnation
        # gate on metrics_report is what now keeps zombies out).
        self.metrics_federation.revive(node_id.hex()[:12])
        self._cluster.adopt_raylet(proxy)
        return {"ok": True, "incarnation": proxy.incarnation}

    def node_id_for_token(self, reg_token: str) -> Optional[NodeID]:
        """Resolve a spawner's one-shot registration token to the node
        id the spawned process registered with."""
        with self._lock:
            return self._reg_tokens.get(reg_token)

    def _handle_unregister_node(self, payload) -> bool:
        node_id = NodeID(payload["node_id"])
        self._cluster.gcs.unregister_raylet(node_id)
        self._drop_proxy(node_id)
        return True

    def _handle_heartbeat(self, payload):
        fenced = self._fence_gate(payload, "heartbeat")
        if fenced is not None:
            return fenced
        node_id = NodeID(payload["node_id"])
        known = self._cluster.gcs.heartbeat_manager.heartbeat(node_id)
        if not known and payload.get("incarnation") is not None:
            # Stamped but unknown to the beat tracker: membership raced
            # out from under the gate (death between gate and here).
            # Tell the sender rather than ACK a beat nobody counted —
            # an ACKed-but-dropped beat is a zombie that never learns.
            # Unstamped (pre-registration) beats stay silently ignored.
            nm = self._cluster.gcs.node_manager
            nm.note_fenced(node_id, "heartbeat")
            return {"fenced": True,
                    "rejected": int(payload["incarnation"]),
                    "incarnation": nm.current_incarnation(node_id)}
        return True

    def _handle_metrics_report(self, payload):
        """Federation ingest: merge one node's registry delta under its
        node_id label (reporter.py precedent — per-node samples riding
        an existing channel up to the head).  Reports from nodes this
        head no longer mirrors are REJECTED — the incarnation fence is
        the general mechanism (subsuming the PR-8 tombstone special
        case): a straggling report from a declared-dead node cannot
        resurrect its federation entry after the death-prune."""
        fenced = self._fence_gate(payload, "metrics_report")
        if fenced is not None:
            return fenced
        node_id = NodeID(payload["node_id"])
        if self._proxy_for(node_id) is None:
            return False
        self.metrics_federation.ingest(node_id.hex()[:12],
                                       payload.get("snapshot"),
                                       full=payload.get("full", False))
        return True

    def _handle_wedge_report(self, payload):
        fenced = self._fence_gate(payload, "wedge_report")
        if fenced is not None:
            return fenced
        return self._handle_wedge_report_admitted(payload)

    def _handle_wedge_report_admitted(self, payload) -> bool:
        """A node's watchdog tripped (or recovered): track its internal
        loop liveness and keep the last wedge evidence for the doctor.
        A 'wedge' downgrades liveness immediately; 'recovered' restores
        it but keeps the report — the evidence IS the point."""
        node_hex = NodeID(payload["node_id"]).hex()[:12]
        event = payload.get("event", "wedge")
        report = payload.get("report") or {}
        from ray_tpu._private.metrics_agent import record_internal
        with self._lock:
            state = self.loop_liveness.setdefault(
                node_hex, {"degraded": False, "wedges": 0,
                           "last_report": None, "last_event_ts": 0.0})
            state["last_event_ts"] = report.get("ts", 0.0)
            if event == "wedge":
                state["degraded"] = True
                state["wedges"] += 1
                state["last_report"] = report
            else:
                state["degraded"] = False
            degraded = state["degraded"]
        flight_recorder.record("node.loop_liveness", node=node_hex,
                               event=event, degraded=degraded)
        record_internal("ray_tpu.node.internal_loop_degraded",
                        1.0 if degraded else 0.0, node=node_hex)
        return True

    def _handle_debug_dump(self, payload):
        """Cluster-wide introspection collection (`ray-tpu doctor`):
        this process's own report plus a bounded parallel fan-out of
        per-node ``debug_dump`` RPCs — a WEDGED node must not be able
        to hang the doctor past the per-node timeout, and an
        unreachable one is itself a finding."""
        from ray_tpu._private.debug.report import handle_debug_dump
        payload = payload or {}
        timeout = float(payload.get("timeout", 10.0))
        out = {"head": handle_debug_dump(payload), "nodes": {}}
        # Membership rollup: liveness state + incarnation + fencing
        # evidence per node (the doctor's partition-tolerance column).
        nm = self._cluster.gcs.node_manager
        membership = {}
        for node_id, info in nm.get_all_node_info().items():
            membership[node_id.hex()[:12]] = {
                "state": info.get("state"),
                "incarnation": info.get("incarnation", 0),
                "fenced_rejections": nm.fenced_count(node_id),
                "fenced_by_verb":
                    dict(nm.fence_rejections.get(node_id, {})),
            }
        out["membership"] = membership
        with self._lock:
            proxies = dict(self._proxies)
            out["liveness"] = {k: {kk: vv for kk, vv in v.items()
                                   if kk != "last_report"}
                               for k, v in self.loop_liveness.items()}
            wedged = {k: v["last_report"]
                      for k, v in self.loop_liveness.items()
                      if v.get("last_report")}
        results: Dict[str, object] = {}
        threads = []

        def collect(node_hex, proxy):
            try:
                results[node_hex] = proxy.client.call(
                    "debug_dump", payload, timeout=timeout)
            except Exception as e:
                results[node_hex] = {"error": f"debug_dump failed: {e}"}

        for node_id, proxy in proxies.items():
            t = threading.Thread(
                target=collect, args=(node_id.hex()[:12], proxy),
                daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=timeout + 2.0)
        # Snapshot before iterating: a collector thread that outlived
        # its join timeout may still insert its (late) result while we
        # read — exactly the wedged-node case the fan-out exists for.
        for node_hex, report in list(results.items()):
            out["nodes"][node_hex] = report
        # A node that never answered (thread still running / no result)
        # is reported as unreachable — with the head-held wedge
        # evidence attached if we have any, which is exactly the case
        # where the node is too wedged to serve its own dump.
        for node_id in proxies:
            node_hex = node_id.hex()[:12]
            if node_hex not in out["nodes"]:
                entry = {"error": "unreachable within timeout"}
                if node_hex in wedged:
                    entry["last_wedge_report"] = wedged[node_hex]
                out["nodes"][node_hex] = entry
        return out

    def _handle_actor_worker_died(self, payload):
        fenced = self._fence_gate(payload, "actor_worker_died")
        if fenced is not None:
            return fenced
        self._cluster.gcs.actor_manager.on_actor_worker_died(
            payload["actor_id"], payload["reason"])
        return True

    def _on_node_death(self, node_id: NodeID):
        self._drop_proxy(node_id)

    def _drop_proxy(self, node_id: NodeID):
        with self._lock:
            proxy = self._proxies.pop(node_id, None)
            dropped_liveness = self.loop_liveness.pop(
                node_id.hex()[:12], None)
        if proxy is not None:
            # Fence the dead mirror BEFORE closing: a lease reply racing
            # the death prune must convert to a rejection, not a worker
            # handle held by nobody.
            proxy.fenced = True
            proxy._stop_reconcile()
        if dropped_liveness is not None:
            # A dead node is not "internally degraded" — its death is
            # the heartbeat plane's story, and a lingering per-node
            # series would grow label cardinality forever under churn:
            # delete it (same promptness as the federation prune below).
            from ray_tpu._private.metrics_agent import \
                get_metrics_registry
            get_metrics_registry().remove_series(
                "ray_tpu.node.internal_loop_degraded",
                (("node", node_id.hex()[:12]),))
        if proxy is not None:
            proxy.client.close()
        # A dead node's federated series must vanish from /metrics now
        # (collector-ownership pruning, made prompt): stale gauges from
        # a dead node read as live signal.
        self.metrics_federation.drop(node_id.hex()[:12])

    # ---- KV ------------------------------------------------------------
    def _handle_kv_get(self, key: bytes) -> Optional[bytes]:
        return self._cluster.gcs.kv.get(key)

    # ---- object plane --------------------------------------------------
    def _owner_inline_blob(self, oid: ObjectID) -> Optional[bytes]:
        """Small returns/puts live in the owner's memory store and are
        never directory-registered; serve them straight from it."""
        core = self._cluster.core_worker
        if core is None:
            return None
        entry = core.memory_store.get_entry(oid)
        if entry is not None and entry.sealed and entry.error is None and \
                isinstance(entry.data, SerializedObject):
            return entry.data.to_bytes()
        return None

    def _handle_fetch_object(self, payload) -> Optional[bytes]:
        oid = ObjectID(payload["object_id"])
        head = self._cluster.head_node
        if head is not None:
            serialized = head.object_store.get_serialized(oid)
            if serialized is not None:
                return serialized.to_bytes()
        blob = self._owner_inline_blob(oid)
        if blob is not None:
            return blob
        # Fallback relay: peers normally pull node-to-node directly
        # (the directory hands them dialable addresses); this path only
        # serves ray-client drivers — whose sole connection is the head —
        # and peers whose direct dial failed.
        head_id = head.node_id if head is not None else None
        for node_id in self._cluster.object_directory.get_locations(oid):
            if node_id == head_id:
                continue
            raylet = self._cluster.gcs.raylet(node_id)
            if raylet is None:
                continue
            serialized = raylet.object_store.get_serialized(oid)
            if serialized is not None:
                self.relay_fetches += 1
                return serialized.to_bytes()
        return None

    def _handle_fetch_value(self, payload):
        """Executor-facing fetch: like ``fetch_object`` but propagates
        error entries (a failed upstream task's return must raise in the
        downstream executor, not read as 'missing').  Returns
        ("ok", bytes) | ("error", pickled exception) | None."""
        import pickle

        oid = ObjectID(payload["object_id"])
        core = self._cluster.core_worker
        if core is not None:
            entry = core.memory_store.get_entry(oid)
            if entry is not None and entry.sealed and \
                    entry.error is not None:
                try:
                    return ("error", pickle.dumps(entry.error))
                except Exception:
                    return ("error", pickle.dumps(
                        exceptions.RayTpuError(str(entry.error))))
        head = self._cluster.head_node
        if head is not None:
            serialized = head.object_store.get_serialized(oid)
            if serialized is not None:
                return self._value_reply(serialized.to_bytes())
        blob = self._owner_inline_blob(oid)
        if blob is not None:
            return self._value_reply(blob)
        # Bytes live on some other registered node: redirect the caller
        # to pull peer-to-peer instead of relaying head-through.
        head_id = head.node_id if head is not None else None
        for node_id in self._cluster.object_directory.get_locations(oid):
            if node_id == head_id:
                continue
            proxy = self._proxy_for(node_id)
            if proxy is not None:
                return ("remote", {"node_id": node_id.binary(),
                                   "host": proxy.address[0],
                                   "port": proxy.address[1]})
        return None

    def _value_reply(self, blob: bytes):
        from ray_tpu._private.config import get_config
        if len(blob) > get_config().object_manager_chunk_size:
            # Hand back a session over the bytes we already hold —
            # re-fetching them through fetch_meta would double the wire
            # and memory cost of every big value.
            meta = self.chunk_server.open_session(blob)
            return ("chunked", meta)   # meta None -> caller retries
        return ("ok", blob)

    def _proxy_for(self, node_id: NodeID) -> Optional[RemoteNodeProxy]:
        with self._lock:
            return self._proxies.get(node_id)

    def _handle_put_inline(self, payload):
        fenced = self._fence_gate(payload, "put_inline")
        if fenced is not None:
            return fenced
        core = self._cluster.core_worker
        if core is None:
            return False
        core.memory_store.put(
            ObjectID(payload["object_id"]),
            SerializedObject.from_bytes(payload["blob"]))
        return True

    def _handle_add_location(self, payload):
        fenced = self._fence_gate(payload, "add_location")
        if fenced is not None:
            return fenced
        self._cluster.object_directory.add_location(
            ObjectID(payload["object_id"]), NodeID(payload["node_id"]),
            size=payload.get("size") or None)
        return True

    def _handle_remove_location(self, payload):
        """A node healed a vanished/stale copy: drop its directory row
        so fetch_value/get_locations stop redirecting pulls to it."""
        fenced = self._fence_gate(payload, "remove_location")
        if fenced is not None:
            return fenced
        self._cluster.object_directory.remove_location(
            ObjectID(payload["object_id"]), NodeID(payload["node_id"]))
        return True

    def _handle_add_partial_location(self, payload):
        """Register a spoke's in-flight pull as a relayable PARTIAL
        directory row; replies with the row's seq (the cycle-free
        ordering relay chains rely on)."""
        fenced = self._fence_gate(payload, "add_partial_location")
        if fenced is not None:
            return None   # partial registration protocol: None = refuse
        directory = self._cluster.object_directory
        if not hasattr(directory, "add_partial_location"):
            return None
        return directory.add_partial_location(
            ObjectID(payload["object_id"]), NodeID(payload["node_id"]))

    def _handle_remove_partial_location(self, payload):
        fenced = self._fence_gate(payload, "remove_partial_location")
        if fenced is not None:
            return fenced
        directory = self._cluster.object_directory
        if hasattr(directory, "remove_partial_location"):
            directory.remove_partial_location(
                ObjectID(payload["object_id"]),
                NodeID(payload["node_id"]))
        return True

    def _node_transfer_load(self, node_id: NodeID) -> Optional[dict]:
        """Outbound-transfer load hint for a directory answer: the
        head's own ledger is read live; spokes' ride their resource
        reports (at most one poll stale)."""
        head = self._cluster.head_node
        if head is not None and node_id == head.node_id:
            return head.object_store.transfer_ledger.load_snapshot()
        proxy = self._proxy_for(node_id)
        if proxy is not None:
            return (proxy._last_report or {}).get("transfer_load")
        return None

    def _handle_get_locations(self, payload):
        """Locations WITH dialable addresses: peers use these to pull
        node-to-node directly (OwnershipBasedObjectDirectory parity —
        the directory answer is what makes the plane peer-to-peer).
        Head-resident copies carry host=None: the asking spoke already
        holds a head connection.  Each row carries the source's
        outbound-load hint (load-aware selection) and partial relay
        rows ride along flagged ``partial`` with their seq — legacy
        spokes that only want full copies filter on the flag."""
        oid = ObjectID(payload["object_id"])
        directory = self._cluster.object_directory
        if hasattr(directory, "get_candidates"):
            rows = directory.get_candidates(oid)
        else:
            rows = [{"node_id": n, "partial": False, "seq": 0}
                    for n in directory.get_locations(oid)]
        out = []
        seen = set()
        for row in rows:
            node_id = row["node_id"]
            entry = {"node_id": node_id.binary(), "host": None,
                     "port": None, "partial": bool(row.get("partial")),
                     "seq": int(row.get("seq") or 0),
                     "size": int(row.get("size") or 0),
                     "load": self._node_transfer_load(node_id)}
            proxy = self._proxy_for(node_id)
            if proxy is not None:
                entry["host"], entry["port"] = proxy.address
            out.append(entry)
            seen.add(node_id.binary())
        head = self._cluster.head_node
        if head is not None and head.node_id.binary() not in seen and \
                self._owner_inline_blob(oid) is not None:
            out.append({"node_id": head.node_id.binary(),
                        "host": None, "port": None, "partial": False,
                        "seq": 0, "load": None})
        return out

    def _handle_get_node_address(self, payload):
        """node_id -> (host, port) a peer can dial, or None for the head
        node / unknown nodes (callers fall back to their head link)."""
        proxy = self._proxy_for(NodeID(payload["node_id"]))
        return None if proxy is None else list(proxy.address)

    def _handle_wait_object(self, payload, reply):
        """Block (server-side, event-driven) until the object has a
        location or the owner's memory store seals it; reply with a node
        id to fetch from, or None on timeout.  Replaces the spoke-side
        20 ms location poll."""
        oid = ObjectID(payload["object_id"])
        timeout = float(payload.get("timeout", 30.0))
        head = self._cluster.head_node
        directory = self._cluster.object_directory
        done = threading.Event()
        state: Dict = {}

        def finish(node_id):
            if done.is_set():
                return
            done.set()
            timer = state.get("timer")
            if timer is not None:
                timer.cancel()
            directory.unsubscribe_location(oid, on_location)
            mem_cb = state.get("mem_cb")
            core = self._cluster.core_worker
            if mem_cb is not None and core is not None:
                core.memory_store.cancel_get_async(oid, mem_cb)
            if node_id is None:
                reply(None)
                return
            entry = {"node_id": node_id.binary(), "host": None,
                     "port": None}
            proxy = self._proxy_for(node_id)
            if proxy is not None:
                entry["host"], entry["port"] = proxy.address
            reply(entry)

        def on_location(node_id):
            finish(node_id)

        if self._owner_inline_blob(oid) is not None and head is not None:
            finish(head.node_id)
            return
        directory.subscribe_location(oid, on_location)
        core = self._cluster.core_worker
        if core is not None and head is not None:
            mem_cb = lambda _entry: finish(head.node_id)  # noqa: E731
            state["mem_cb"] = mem_cb
            core.memory_store.get_async(oid, mem_cb)
        if not done.is_set():
            timer = threading.Timer(timeout, lambda: finish(None))
            timer.daemon = True
            state["timer"] = timer
            timer.start()
            if done.is_set():
                timer.cancel()

    # ---- lifecycle -----------------------------------------------------
    def stop(self):
        with self._lock:
            proxies = list(self._proxies.values())
            self._proxies.clear()
        for p in proxies:
            p.client.close()
        # Stop the server FIRST: a metrics_report still in flight could
        # otherwise re-create a federation entry after the purge.  Then
        # drop the entries — the registry is process-global, so a
        # stopped cluster's federated series must not linger until GC
        # happens to collect the owners.
        self.server.stop()
        for node_id in self.metrics_federation.node_ids():
            self.metrics_federation.drop(node_id)
