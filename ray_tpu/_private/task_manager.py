"""Owner-side task state: pending set, retries, lineage.

Parity: reference ``src/ray/core_worker/task_manager.{h,cc}`` — tracks every
submitted task until its returns are sealed; retries failed tasks up to
``max_retries``; pins task specs for lineage reconstruction
(``lineage_pinning_enabled``); resubmits the creating task when a lost
object must be reconstructed (``object_recovery_manager.cc``).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ray_tpu import exceptions
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu._private.debug import diag_condition, diag_rlock


class _PendingTask:
    __slots__ = ("spec", "retries_left", "status")

    def __init__(self, spec: TaskSpec, retries_left: int):
        self.spec = spec
        self.retries_left = retries_left
        self.status = "PENDING"


class TaskManager:
    def __init__(self, core_worker):
        self._core = core_worker
        self._lock = diag_rlock("TaskManager._lock")
        self._pending: Dict[TaskID, _PendingTask] = {}
        # Lineage: task specs pinned while their return objects may need
        # reconstruction (reference: TaskManager lineage map), bounded
        # by ``max_lineage_bytes`` of inlined-arg payload: beyond the
        # budget the OLDEST pins are dropped (insertion order), so the
        # newest — most likely still needed — lineage survives.  An
        # evicted spec makes its objects non-reconstructable, exactly
        # the doctor's "lineage=evicted" hint.
        self._lineage: Dict[TaskID, TaskSpec] = {}
        self._lineage_sizes: Dict[TaskID, int] = {}
        self._lineage_bytes = 0
        self._completion_cv = diag_condition(self._lock, name="TaskManager._lock")

    @staticmethod
    def _spec_lineage_bytes(spec: TaskSpec) -> int:
        """Approximate pinned footprint: inlined serialized args + a
        flat per-spec overhead for the metadata fields."""
        total = 512
        for arg in spec.args:
            v = getattr(arg, "value", None)
            if arg.is_inline and v is not None:
                total += len(getattr(v, "inband", b"") or b"")
                for buf in getattr(v, "buffers", ()) or ():
                    total += getattr(buf, "nbytes", 0)
        return total

    # ---- submission lifecycle ------------------------------------------
    def add_pending_task(self, spec: TaskSpec) -> None:
        cfg = get_config()
        with self._lock:
            self._pending[spec.task_id] = _PendingTask(spec, spec.max_retries)
            if cfg.lineage_pinning_enabled:
                sz = self._spec_lineage_bytes(spec)
                self._lineage[spec.task_id] = spec
                self._lineage_sizes[spec.task_id] = sz
                self._lineage_bytes += sz
                budget = cfg.max_lineage_bytes
                while self._lineage_bytes > budget and len(self._lineage) > 1:
                    oldest = next(iter(self._lineage))
                    if oldest == spec.task_id:
                        break
                    self._lineage.pop(oldest)
                    self._lineage_bytes -= self._lineage_sizes.pop(oldest, 0)
        # Register owned return objects with lineage pointers.
        rc = self._core.reference_counter
        for oid in spec.return_ids:
            rc.add_owned_object(oid, lineage_task_id=spec.task_id)
        rc.add_submitted_task_refs(
            spec.arg_object_ids() + list(spec.borrowed_ids))

    def is_pending(self, task_id: TaskID) -> bool:
        with self._lock:
            return task_id in self._pending

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def get_spec(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            t = self._pending.get(task_id)
            if t is not None:
                return t.spec
            return self._lineage.get(task_id)

    # ---- completion/failure (called from transport) ---------------------
    def complete_task(self, spec: TaskSpec):
        from ray_tpu.gcs import task_events
        with self._lock:
            t = self._pending.pop(spec.task_id, None)
            self._completion_cv.notify_all()
        if t is None:
            # Stale/duplicate completion: a retried task's original
            # attempt landing after the retry already transitioned it,
            # or two failure paths racing on node death.  The first
            # terminal transition already removed the submitted-task
            # refs — removing them again would drive the args' counts
            # negative and prematurely free objects the driver still
            # holds (observed as lost-object + evicted-lineage in the
            # sigkill chaos test).
            return
        task_events.emit(self._core.cluster, spec.task_id,
                         task_events.FINISHED)
        self._core.reference_counter.remove_submitted_task_refs(
            spec.arg_object_ids() + list(spec.borrowed_ids))

    def fail_or_retry(self, spec: TaskSpec, error: BaseException,
                      resubmit: Callable[[TaskSpec], None]) -> bool:
        """Returns True if the task will be retried."""
        retryable = isinstance(error, (exceptions.WorkerCrashedError,
                                       exceptions.NodeDiedError)) or \
            (spec.retry_exceptions and isinstance(error, exceptions.TaskError))
        with self._lock:
            t = self._pending.get(spec.task_id)
            if t is None:
                return False
            if retryable and t.retries_left > 0:
                t.retries_left -= 1
                do_retry = True
            elif not retryable and not isinstance(error, exceptions.TaskError) \
                    and t.retries_left > 0:
                # System failures (lease/dispatch) always consume a retry.
                t.retries_left -= 1
                do_retry = True
            else:
                do_retry = False
            attempt = spec.max_retries - t.retries_left
        if do_retry:
            from ray_tpu.gcs import task_events
            # Retry re-enters the lifecycle at PENDING_ARGS_AVAIL with a
            # bumped attempt counter (reference: attempt_number on
            # TaskEvents; retries are new attempts of the same task id).
            task_events.emit(self._core.cluster, spec.task_id,
                             task_events.PENDING_ARGS_AVAIL,
                             name=spec.function_name, attempt=attempt)
            resubmit(spec)
            return True
        self.fail_task(spec, error)
        return False

    def fail_task(self, spec: TaskSpec, error: BaseException):
        """Store the error into all return objects so gets raise."""
        from ray_tpu.gcs import task_events
        with self._lock:
            t = self._pending.pop(spec.task_id, None)
            self._completion_cv.notify_all()
        if t is None:
            # Duplicate terminal transition (see complete_task): the
            # task already completed or failed — don't double-remove
            # arg refs, and don't overwrite sealed returns with errors.
            return
        task_events.emit(self._core.cluster, spec.task_id,
                         task_events.FAILED, error=repr(error))
        for oid in spec.return_ids:
            self._core.memory_store.put_error(oid, _user_error(error))
        self._core.reference_counter.remove_submitted_task_refs(
            spec.arg_object_ids() + list(spec.borrowed_ids))

    # ---- lineage / reconstruction ---------------------------------------
    def lineage_spec_for_object(self, object_id: ObjectID) -> Optional[TaskSpec]:
        with self._lock:
            return self._lineage.get(object_id.task_id())

    def evict_lineage(self, task_id: TaskID):
        with self._lock:
            if self._lineage.pop(task_id, None) is not None:
                self._lineage_bytes -= self._lineage_sizes.pop(task_id, 0)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until no tasks are pending (driver exit parity)."""
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._pending:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._completion_cv.wait(timeout=remaining if remaining is None
                                         else min(remaining, 0.5))
            return True


def _user_error(error: BaseException) -> BaseException:
    if isinstance(error, exceptions.TaskError):
        return error
    if isinstance(error, exceptions.RayTpuError):
        return error
    return exceptions.RayTpuError(str(error))
