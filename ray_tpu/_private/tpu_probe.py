"""Bounded-timeout accelerator probe.

A sick TPU backend hangs ``jax.devices()`` indefinitely (round-5
evidence: ``import jax; jax.devices()`` blocked >120 s and poisoned both
driver artifacts).  Nothing that merely needs a DECISION — "is the chip
usable?" — may pay that risk in its own process.  This helper runs the
backend initialization in a subprocess with a hard timeout and a couple
of retries, and reports a structured verdict the caller can act on
(re-exec on CPU, emit a skip row, fall back to a scaled problem).

Used by ``__graft_entry__.dryrun_multichip`` and ``bench.py``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional

_PROBE_CODE = (
    "import json, sys\n"
    "import jax\n"
    "devs = jax.devices()\n"
    "print(json.dumps({'backend': jax.default_backend(),"
    " 'device_count': len(devs),"
    " 'device_kind': getattr(devs[0], 'device_kind', '?')}))\n"
)


def probe_backend(timeout: float = 60.0, retries: int = 2,
                  env: Optional[dict] = None) -> dict:
    """Initialize the default jax backend in a subprocess, bounded.

    Returns ``{"ok": True, "backend", "device_count", "device_kind",
    "attempts"}`` on success, or ``{"ok": False, "error", "timed_out",
    "attempts"}`` when every attempt hung or crashed.  The parent
    process never initializes a backend here."""
    last_error = "unknown"
    timed_out = False
    attempts = 0
    for attempts in range(1, retries + 2):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                env=dict(env) if env is not None else dict(os.environ),
                capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            timed_out = True
            last_error = f"backend init exceeded {timeout:.0f}s"
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            try:
                info = json.loads(proc.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                last_error = "unparseable probe output"
                continue
            info.update({"ok": True, "attempts": attempts})
            return info
        timed_out = False
        last_error = (proc.stderr or "probe crashed")[-500:]
    return {"ok": False, "error": last_error, "timed_out": timed_out,
            "attempts": attempts}


def chip_unavailable_marker(probe: dict, **extra) -> str:
    """One structured JSON line announcing an unusable accelerator —
    drivers grep for ``"event": "chip_unavailable"`` instead of parsing
    tracebacks."""
    row = {"event": "chip_unavailable",
           "error": probe.get("error"),
           "timed_out": bool(probe.get("timed_out")),
           "attempts": probe.get("attempts")}
    row.update(extra)
    return json.dumps(row)


def backend_initialized_in_process() -> bool:
    """True when THIS process already has a live jax backend — checking
    costs nothing and triggers no initialization."""
    if sys.modules.get("jax") is None:
        return False
    xb = sys.modules.get("jax._src.xla_bridge")
    return bool(getattr(xb, "_backends", None))
