"""128-bit opaque identifiers for cluster entities.

TPU-native re-design of the reference ID scheme (reference:
``src/ray/common/id.h:1`` — TaskID/ObjectID/ActorID/NodeID/JobID as fixed-width
binary IDs with nil sentinels).  We keep the same external contract — fixed
width, hex round-trip, ``is_nil``, hashable, orderable — but the representation
is a plain ``bytes`` payload; there is no embedded structure decoding on the
hot path, and object indices are carried separately in the object-ref metadata.
"""

from __future__ import annotations

import os
import random
import threading

from ray_tpu._private.debug.lock_order import diag_lock

_ID_SIZE = 16  # 128-bit, matches reference UniqueID size.

# ID generation is on the task-submission hot path (TaskID + one
# ObjectID per return), and ``os.urandom`` is a real syscall per call —
# measured at >200us under sandboxed kernels, which made it THE
# dominant cost of ``remote()``.  IDs need uniqueness, not
# cryptographic strength: draw them from a per-thread PRNG seeded once
# from urandom (+ pid + thread id, so forks and threads can't share a
# stream).
_rand_local = threading.local()


def _random_bytes(n: int) -> bytes:
    rng = getattr(_rand_local, "rng", None)
    if rng is None or _rand_local.pid != os.getpid():
        seed = int.from_bytes(os.urandom(16), "little") \
            ^ (os.getpid() << 64) ^ threading.get_ident()
        rng = _rand_local.rng = random.Random(seed)
        _rand_local.pid = os.getpid()
    return rng.getrandbits(n * 8).to_bytes(n, "little")


class BaseID:
    """Fixed-width binary id. Immutable, hashable, hex round-trippable."""

    __slots__ = ("_binary", "_hash")
    SIZE = _ID_SIZE

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._binary = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls):
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def is_nil(self) -> bool:
        return self._binary == b"\xff" * self.SIZE

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(self) is type(other) and self._binary == other._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = 4

    _counter = 0
    _lock = diag_lock("ids._lock")

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(cls.SIZE, "little"))

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls.from_int(cls._counter)

    def int_value(self) -> int:
        return int.from_bytes(self._binary, "little")


class UniqueID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class TaskID(BaseID):
    """Task id.  Reference embeds parent/actor info in the byte layout
    (``src/ray/common/id.h``); we carry that in the TaskSpec instead and keep
    the id opaque."""

    SIZE = 16

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(job_id.binary().ljust(cls.SIZE, b"\x00"))


class ActorID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class ObjectID(BaseID):
    """Object id = owning task id (16B) + little-endian put/return index.

    Mirrors the reference's ``ObjectID::FromIndex`` scheme
    (``src/ray/common/id.h``) so that lineage — "which task created this
    object" — is recoverable from the id alone.
    """

    SIZE = 24

    @classmethod
    def from_index(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(8, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:16])

    def index(self) -> int:
        return int.from_bytes(self._binary[16:], "little")


class FunctionID(BaseID):
    pass


NIL_NODE_ID = NodeID.nil()
NIL_ACTOR_ID = ActorID.nil()
