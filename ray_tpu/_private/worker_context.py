"""Thread-local execution context for worker threads.

Parity: reference ``WorkerContext`` (src/ray/core_worker/context.cc) — which
task/actor a thread is currently executing, for runtime_context, nested task
ownership (parent_task_id, depth) and PG capture.
"""

from __future__ import annotations

import threading

_tls = threading.local()


class ExecutionContext:
    __slots__ = ("task_spec", "worker", "node", "actor_instance")

    def __init__(self, task_spec=None, worker=None, node=None,
                 actor_instance=None):
        self.task_spec = task_spec
        self.worker = worker
        self.node = node
        self.actor_instance = actor_instance


def set_context(ctx):
    _tls.ctx = ctx


def get_context() -> ExecutionContext:
    return getattr(_tls, "ctx", None) or ExecutionContext()


def clear_context():
    _tls.ctx = None


def current_task_spec():
    return get_context().task_spec


def in_task() -> bool:
    return get_context().task_spec is not None
