"""Env-overridable framework configuration.

TPU-native equivalent of the reference's macro-generated config
(``src/ray/common/ray_config_def.h:46-66`` — 141 ``RAY_CONFIG(type, name,
default)`` entries, each overridable from env ``RAY_{name}``, plus a JSON
``_system_config`` propagated to all daemons via ``RayConfig::initialize``,
``src/ray/common/ray_config.cc:29``).

Here every dataclass field is overridable from env ``RAY_TPU_{NAME}`` and from
the ``_system_config`` dict passed to :func:`ray_tpu.init`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Optional

from ray_tpu._private.debug.lock_order import diag_lock


@dataclasses.dataclass
class Config:
    # ------ scheduler (reference: ray_config_def.h:138,463,533,342) ------
    #: Utilization below which the hybrid policy packs instead of spreads.
    scheduler_spread_threshold: float = 0.5
    #: Prefer non-TPU nodes for tasks that don't require TPU (reference:
    #: ``scheduler_avoid_gpu_nodes``, ray_config_def.h:533).
    scheduler_avoid_tpu_nodes: bool = True
    #: Which backend solves the task->node assignment each tick:
    #: "native" = greedy per-task python/numpy policy (reference parity),
    #: "jax"    = batched TPU bin-packing kernel (the north star) with
    #:            device-resident world state and validated native
    #:            fallback.  Default since round 3.
    scheduler_backend: str = "jax"

    #: Fuse the per-class waterfill into one Mosaic (Pallas) kernel on
    #: TPU; falls back to the jnp scan path automatically on failure.
    scheduler_pallas_fill: bool = True
    #: Heterogeneity cost weight (Gavel-style effective-rate scaling):
    #: slower nodes (per the ray_tpu.throughput / accel_throughput node
    #: labels) cost this much extra utilization at full rate spread.
    #: 0 disables the term; 1/16 of weight = one fill bucket.
    scheduler_het_weight: float = 0.25
    #: Arg-locality cost weight: a node holding ALL of a class's queued
    #: argument bytes gets this much utilization bonus (negative cost).
    #: 0 disables the term.
    scheduler_locality_weight: float = 0.5
    #: Placement-group bundle packing backend: "auto" routes through the
    #: TPU bundle kernel when jax is importable and the cluster has at
    #: least pg_kernel_min_nodes nodes (greedy numpy fallback below
    #: that, and on any kernel failure), "force" always kernels,
    #: "off" always greedy.
    pg_kernel_backend: str = "auto"
    pg_kernel_min_nodes: int = 32
    #: Autoscaler demand-solve backend: "auto" routes
    #: get_bin_pack_residual / get_nodes_for through the batched kernel
    #: when nodes x demand-classes >= autoscaler_kernel_min_cells
    #: (exact numpy below, and on any kernel failure), "force" / "off"
    #: as above.
    autoscaler_kernel_backend: str = "auto"
    autoscaler_kernel_min_cells: int = 2048
    #: Pod-sharded solve: shard the (classes x nodes) waterfill /
    #: solve-tick / bundle-pack along the NODE axis across the local
    #: devices (shard_map over a 1-D mesh, cross-shard prefix/argmax
    #: reductions per bucket step).  "auto" shards when more than one
    #: device is visible AND the cluster has at least
    #: solver_shard_min_nodes nodes; "force" shards whenever >1 device
    #: exists (tests); "off" never shards.  The single-device path
    #: stays the default below the gate — sharding a small solve pays
    #: collective latency for nothing.
    solver_shard_backend: str = "auto"
    solver_shard_min_nodes: int = 4096
    #: Event-buffer lock striping: per-thread striped sub-buffers
    #: (round-robin thread->stripe binding) drained and merged by the
    #: flusher.  1 = the old single-lock buffer.
    task_event_stripes: int = 8
    #: Max lease requests in flight per scheduling class
    #: (ray_config_def.h:342).  Batched lease requests count each
    #: entry against this cap.
    max_pending_lease_requests_per_scheduling_category: int = 10
    #: Max lease entries coalesced into ONE request_worker_lease_batch
    #: round-trip (the dispatch fast path: a same-class burst leases up
    #: to this many workers per RPC instead of one).  1 disables
    #: batching (every lease rides the single-lease RPC).
    lease_batch_size: int = 10
    #: Retry delay for lease-batch entries the raylet returned as
    #: ``backlog`` (feasible but no capacity this tick) when nothing
    #: else — a completion, a new submit, a lease reply — re-pumps the
    #: class first.  Pure fallback; the common re-pump is event-driven.
    lease_backlog_retry_ms: int = 20
    #: How long an idle LEASED worker is parked submitter-side before
    #: its lease is returned to the raylet (lease pipelining: a
    #: same-class task submitted within the window is pushed directly,
    #: zero scheduling round-trips).  Trade-off: a parked lease HOLDS
    #: its resource reservation for up to the window, so other
    #: scheduling classes see less capacity; keep it at request-gap
    #: scale.  0 = off (return leases immediately, current behavior).
    worker_lease_keepalive_ms: int = 0
    #: Submit-side flow control: when a scheduling class's transport
    #: queue is deeper than this at submit time, the submitting thread
    #: yields the GIL (``time.sleep(0)``) so executing workers can
    #: drain — a tight submission loop otherwise starves the very
    #: pipeline it is filling and every queued task's latency grows by
    #: the imbalance.  A yield, not a block: semantics are unchanged,
    #: and shallow queues never hit it.  0 disables.
    submit_backpressure_depth: int = 64
    #: Event-driven scheduling wakeup debounce: a task arrival /
    #: resource release schedules the tick this many ms out, and
    #: further wakeups inside the window coalesce into that one tick —
    #: a submission burst becomes one batched solve instead of one tick
    #: per task.  0 = post the tick immediately (no coalescing).  The
    #: periodic event_loop_tick_ms tick remains as fallback.
    scheduler_wakeup_debounce_ms: float = 1.0
    #: GCS-side actor scheduling (ray_config_def.h:463).
    gcs_actor_scheduling_enabled: bool = False

    #: Reconnect-reconcile sweep exempts lease grants younger than this
    #: (their grant reply may legitimately still be in flight).
    lease_reconcile_grace_s: float = 5.0
    #: Per-attempt bound on head->node lease RPCs (request/return over
    #: the wire).  A blackholed request (asymmetric partition: the node
    #: heartbeats but cannot receive) would otherwise strand the
    #: submitter forever — the bounded attempts retry under one dedup
    #: token (a slow-but-delivered first attempt is replayed, never
    #: re-granted) and exhausted attempts surface as a lease rejection
    #: the submitter's transient re-lease machinery absorbs.  Keep WELL
    #: above legitimate dep-wait lease holds.
    lease_rpc_timeout_s: float = 30.0

    # ------ failure detection (ray_config_def.h:51-55) ------
    raylet_heartbeat_period_milliseconds: int = 100
    num_heartbeats_timeout: int = 30
    #: Missed beats before a node is marked SUSPECT (published; the
    #: scheduler masks suspect nodes for NEW placements while actors /
    #: objects / placement groups stay untouched).  A transient
    #: partition that heals between this and num_heartbeats_timeout
    #: costs a placement pause, not a node death.  Must be below
    #: num_heartbeats_timeout; the gap is the "suspect grace".
    num_heartbeats_suspect: int = 15

    # ------ object store ------
    #: Objects larger than this are promoted to the node (plasma-equivalent)
    #: store instead of the in-process memory store (reference: 100KB
    #: promotion threshold in CoreWorker::Put).
    max_direct_call_object_size: int = 100 * 1024
    #: Per-node object store capacity in bytes before spilling kicks in.
    object_store_memory: int = 2 * 1024 * 1024 * 1024
    #: Spill when store utilization exceeds this fraction.
    object_spilling_threshold: float = 0.8
    #: Min number of objects batched into one spill operation
    #: (reference: local_object_manager.h min_spilling_size).
    min_spilling_size: int = 100 * 1024 * 1024
    #: How long an over-capacity create/put/transfer reservation may
    #: queue for space (retried as seals/evictions/spills free room)
    #: before ObjectStoreFullError surfaces (reference:
    #: oom_grace_period_s over the plasma create_request_queue).
    object_store_full_grace_period_s: float = 10.0
    #: Delay between retries while a queued create waits for space.
    object_store_full_retry_ms: int = 20
    #: Use the native C++ shared-memory store when available.
    use_native_object_store: bool = True
    #: Chunk size for node-to-node object transfer (object_manager.cc).
    object_manager_chunk_size: int = 5 * 1024 * 1024
    #: In-flight chunk requests per pull transfer: the receiver keeps a
    #: window of this many pipelined chunk RPCs open to hide round-trip
    #: latency (push_manager.cc ack window / pull retry flow).
    object_transfer_pipeline_depth: int = 8
    #: Sender-side transfer admission: max concurrent OUTBOUND transfer
    #: sessions per store (chunk sessions + in-process store-to-store
    #: copies share the cap).  Excess pulls queue FIFO instead of
    #: thrashing every session's window (push_manager.cc bounded
    #: chunks-in-flight, made a per-store budget).
    object_transfer_max_outbound_sessions: int = 4
    #: How long a ``fetch_meta`` waits in the sender's FIFO admission
    #: queue before replying ``busy`` (the receiver then backs off or
    #: re-selects another source).
    object_transfer_admission_wait_s: float = 1.0
    #: Chunk-level relay: a node mid-pull registers a PARTIAL location
    #: row and serves the already-assembled prefix of its in-flight
    #: transfer to downstream pullers, so a 1->N broadcast completes as
    #: a pipelined chain/tree instead of N full copies out of the
    #: origin.  Off = every pull streams from a full copy only.
    object_transfer_relay_enabled: bool = True
    #: Source selection for pulls with multiple known locations:
    #: "load" weighs candidates by live per-source outbound load
    #: (sessions + queue + in-flight bytes), "first" keeps the naive
    #: first-directory-row choice (the pre-relay behavior; the bench's
    #: naive arm).
    object_transfer_source_selection: str = "load"
    #: Server-side wait for the assembly watermark to advance past a
    #: relay chunk request before replying ``pending`` (the receiver
    #: re-requests that chunk).
    object_transfer_relay_wait_s: float = 2.0

    # ------ core worker / task path ------
    #: Args at or below this size are inlined into the task spec
    #: (reference: task_rpc_inlined_bytes_limit / put threshold).
    task_args_inline_bytes_limit: int = 100 * 1024
    #: Default max retries for normal tasks (reference: default 3).
    task_max_retries: int = 3
    #: Lineage pinning for reconstruction (ray_config_def.h:97,110).
    lineage_pinning_enabled: bool = True
    #: Max lineage bytes kept per owner before disabling reconstruction.
    max_lineage_bytes: int = 1024 * 1024 * 1024
    #: Max recursion depth when reconstructing a lost object whose
    #: creating task's args are themselves lost (object_recovery_manager
    #: parity: recovery walks the lineage DAG, bounded).
    max_lineage_reconstruction_depth: int = 10
    #: Base of the per-task exponential backoff between repeated
    #: reconstruction attempts of the same creating task.
    lineage_reconstruction_backoff_s: float = 0.2

    # ------ worker pool ------
    #: "thread" = executor threads in the node process (default; one
    #: process per host owns the TPU chips); "process" = real OS worker
    #: processes spawned via worker_main and driven over the framed-RPC
    #: wire (reference StartWorkerProcess parity, worker_pool.h:428).
    worker_process_mode: str = "thread"
    #: Soft cap of idle workers kept alive per node (ray_config_def.h:129).
    num_workers_soft_limit: int = 64
    #: Warm-worker prestart target (reference ``PrestartWorkers``,
    #: worker_pool.h:350): when queued work outnumbers idle+starting
    #: workers, the dispatch loop starts workers AHEAD of pop_worker up
    #: to this many total, so a burst doesn't pay per-task worker
    #: startup inline.  Memory trade-off: every prestarted worker holds
    #: a thread stack (thread mode) or a whole Python interpreter
    #: (process mode, tens of MB each) even if the burst never
    #: materializes — size it to expected burst width, not max_workers.
    #: 0 = off (workers start lazily in pop_worker, current behavior).
    num_prestart_workers: int = 0
    #: Also prestart from the SUBMIT edge (cluster task manager queue
    #: arrival), not just the local dispatch loop — fires before
    #: scheduling, so workers warm while the solve runs.  No effect
    #: unless num_prestart_workers > 0.
    prestart_on_submit: bool = False
    #: Maximum workers starting up concurrently (reference semantics:
    #: a throttle on spawns, NOT a total cap).
    maximum_startup_concurrency: int = 64
    #: Process-wide (ALL pools in this OS process) cap on workers in
    #: startup concurrently — the cluster-envelope startup-storm
    #: throttle: per-node caps alone let N nodes × per-node cap spawns
    #: land at once on one shared box.  A pop over the cap returns None
    #: (the dispatch tick retries, same contract as the per-node cap).
    #: 0 disables the global gate.
    worker_global_startup_concurrency: int = 128
    #: Stagger between consecutive background prestart spawns
    #: (milliseconds) so a prestart storm ramps instead of spiking.
    #: Only the throwaway prestart thread sleeps; pop_worker never does.
    worker_startup_stagger_ms: float = 0.0
    #: Hard per-node worker cap (runaway backstop; the envelope needs
    #: thousands of dedicated actor workers, reference supports 10k+).
    max_workers_per_node: int = 20_000
    #: Mirror process-worker stdout/stderr lines onto the driver's
    #: terminal via the worker_logs pubsub channel (reference
    #: log_to_driver / log_monitor.py behavior).
    log_to_driver: bool = True

    # ------ rpc ------
    #: Dispatch threads per RpcServer; requests beyond BOTH the pool and
    #: its queue get dedicated threads so blocking handlers can never
    #: deadlock the pool (reference: grpc server completion-queue
    #: thread pool).
    rpc_dispatch_pool_size: int = 64
    #: Attempts for RpcClient.call on verbs classified retryable in
    #: rpc/verbs.py (timeout / connection loss only — a remote handler
    #: exception is deterministic and never retried).
    rpc_retry_attempts: int = 3
    #: Base of the exponential backoff between those retry attempts.
    rpc_retry_backoff_s: float = 0.2
    #: Server-side dedup window (entries) for requests carrying a
    #: client-minted dedup token: the handler of a non-idempotent verb
    #: runs once per token; duplicates — client retries AND duplicated
    #: wire deliveries — get the recorded reply.  Size it well above
    #: (concurrent in-flight mutating requests x retry attempts).
    rpc_dedup_window_size: int = 512

    # ------ GCS ------
    gcs_storage_backend: str = "memory"  # "memory" | "file"
    #: Period of the GCS resource usage poll/broadcast loop
    #: (reference: ray_syncer.h broadcast thread).
    gcs_resource_broadcast_period_milliseconds: int = 100
    #: Head-side registration admission: ``register_node`` handlers
    #: running concurrently beyond this get ``{"busy": True,
    #: "retry_after_ms"}`` instead of a proxy dial — fan-in
    #: backpressure for a 64-host registration storm (the node host
    #: retries with jittered backoff).  0 disables the gate.
    head_registration_concurrency: int = 8

    # ------ misc ------
    event_loop_tick_ms: int = 5
    metrics_report_interval_ms: int = 2_000
    temp_dir: str = "/tmp/ray_tpu"
    #: Enable OpenTelemetry-style span capture (tracing_helper.py parity).
    tracing_enabled: bool = False

    # ------ causal job profiler (gcs/job_graph.py) ------
    #: Arms provenance capture end-to-end: parent/arg-ids stamped onto
    #: submit-side task events, terminal records copied into the per-job
    #: graph store, and object-plane spans (transfer/spill/restore)
    #: force-recorded so `ray-tpu profile` can attribute edge time.
    #: Off = the pre-profiler pipeline, byte-for-byte (the bench's
    #: armed-vs-off overhead row toggles exactly this).
    job_profiler_enabled: bool = True
    #: Bounded graph store: jobs tracked (LRU-evicted beyond this)...
    job_graph_max_jobs: int = 16
    #: ...and terminal task records kept per job (oldest-first evicted;
    #: the profile reports the eviction count as a coverage caveat).
    job_graph_max_tasks: int = 20_000

    # ------ heartbeat-channel shipping budget ------
    #: Per-heartbeat-ship-window byte budget for the node-side timeline
    #: span shipper (unused budget carries over, capped at 4 windows):
    #: bounds observability's share of the heartbeat channel so a span
    #: storm cannot congest the control plane at 64-node scale.
    timeline_ship_budget_bytes: int = 262_144
    #: Shared per-beat byte budget for EVERYTHING observability ships
    #: on the heartbeat channel (metrics deltas + timeline spans).  The
    #: liveness beat itself is never charged: when a beat's payloads
    #: would exceed the budget, the metrics delta is shed (the shipper
    #: force-fulls so the next admitted report resyncs — deferral, not
    #: loss) and the timeline shipper gets only the leftover budget —
    #: congestion sheds telemetry, never liveness.  Shed bytes are
    #: observable as ``ray_tpu_heartbeat_shed_bytes``.  0 = unbounded.
    heartbeat_payload_budget_bytes: int = 1_048_576

    # ------ introspection plane (flight recorder / watchdog) ------
    #: Always-on per-process decision ring (debug.flight_recorder):
    #: scheduler tick summaries, lease-batch vectors, transfer source
    #: selections, spill/restore/reconstruction attempts, create-queue
    #: admits, fault firings.  Dumped by `ray-tpu doctor`, wedge
    #: reports and crash paths.
    flight_recorder_enabled: bool = True
    #: Ring capacity in fixed slots (overwrites oldest; O(slots) memory).
    flight_recorder_slots: int = 512
    #: Stall watchdog over event loops and pump threads: emits wedge
    #: reports (thread stacks + held locks + recorder tail) to a crash
    #: file and to the head.  Report-only — never kills anything.
    watchdog_enabled: bool = True
    #: A loop handler running longer than this (or queued work making
    #: no progress for this long) is a wedge.  0 disables detection
    #: while keeping the beat bookkeeping.
    loop_stall_budget_s: float = 10.0
    #: Watchdog poll cadence (clamped to budget/4).
    watchdog_poll_interval_s: float = 0.5
    #: Per-process cap on wedge/crash files kept in <temp_dir>/wedges:
    #: after each write the oldest files beyond this are pruned (64
    #: hosts under a chaos schedule otherwise grow the directory
    #: without bound).  Dropped files are counted into the
    #: introspection metrics; a clean shutdown removes this process's
    #: remaining files.  0 = unbounded.
    wedge_files_keep: int = 20

    # ------ serve (inference plane) ------
    #: Replica placement backend for serve deployments: "auto" routes
    #: replica starts through the pack-mode TPU kernel solve when the
    #: cluster has at least serve_kernel_min_nodes nodes (DEFAULT
    #: placement below that, and on any solve failure), "force" always
    #: solves, "off" always DEFAULT placement.
    serve_kernel_placement: str = "auto"
    serve_kernel_min_nodes: int = 2
    #: Pipeline ingress inputs at least this large are put ONCE into
    #: the object store and handed to every stage as an ObjectRef (the
    #: zero-copy object-id handoff) instead of being pickled into each
    #: stage's task args.  0 forces the handoff for every input;
    #: negative disables it.
    serve_zero_copy_threshold_bytes: int = 65_536
    #: How many times Router.call re-assigns a request whose replica
    #: died mid-flight before surfacing ReplicaDiedError.  User
    #: exceptions are NEVER retried.
    serve_request_retries: int = 3
    #: Cadence of the router's queue-depth reports to the controller
    #: (the autoscaler's queue signal).  Idle routers go silent after
    #: one zero report regardless of cadence.
    serve_router_report_interval_s: float = 0.25

    @classmethod
    def from_env(cls, system_config: Optional[dict] = None) -> "Config":
        cfg = cls()
        for f in dataclasses.fields(cls):
            env_key = "RAY_TPU_" + f.name.upper()
            # Also honor the reference's RAY_<name> convention.
            raw = os.environ.get(env_key, os.environ.get("RAY_" + f.name))
            if raw is not None:
                setattr(cfg, f.name, _parse(raw, f.type, getattr(cfg, f.name)))
        if system_config:
            for k, v in system_config.items():
                if not hasattr(cfg, k):
                    raise ValueError(f"Unknown system config key: {k}")
                setattr(cfg, k, v)
        return cfg

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def _parse(raw: str, ftype, default):
    t = type(default)
    if t is bool:
        return raw.lower() in ("1", "true", "yes")
    if t is int:
        return int(raw)
    if t is float:
        return float(raw)
    return raw


_global_config: Optional[Config] = None
_lock = diag_lock("config._lock")


def get_config() -> Config:
    """Process-wide config singleton (initialized lazily from env)."""
    global _global_config
    with _lock:
        if _global_config is None:
            _global_config = Config.from_env()
        return _global_config


def initialize_config(system_config: Optional[dict] = None) -> Config:
    global _global_config
    with _lock:
        _global_config = Config.from_env(system_config)
        return _global_config
