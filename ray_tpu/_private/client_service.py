"""The client-runtime wire surface, shared by every host of client-mode
core workers.

Parity: reference Ray Client (``src/ray/protobuf/ray_client.proto:300``
``RayletDriver`` + ``python/ray/util/client/server/``): remote drivers
(``init(address="ray-tpu://...")``) AND process-mode workers
(``worker_main`` nested API) both drive the cluster through the same
handlers — submissions ship as locally-built TaskSpecs, ownership stays
with the serving core worker.

One implementation, two hosts: the HeadService (remote drivers) and the
WorkerHostService (process workers).  Big ``get_value`` replies hand
back a chunk session instead of one oversized frame.
"""

from __future__ import annotations

import pickle
from typing import Callable, Optional

from ray_tpu import exceptions
from ray_tpu._private.ids import JobID, WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.serialization import (
    SerializedObject, deserialize, serialize)


def register_client_surface(server, *, core: Callable, kv,
                            actor_manager: Callable, node_id_fn: Callable,
                            namespace_fn: Optional[Callable] = None,
                            chunk_server=None,
                            pin_cb: Optional[Callable] = None):
    """Register the remote-driver API.

    ``core``/``actor_manager``/``node_id_fn`` are zero-arg callables
    (late-bound: the backing objects can be swapped, e.g. on GCS
    restart).  ``pin_cb(worker_id_hex, object_id)`` scopes put-object
    pins to a client's lifetime where the host tracks one.
    """

    def runtime_info(_payload) -> dict:
        c = core()
        ns = namespace_fn() if namespace_fn else ""
        return {
            "job_id": getattr(c, "job_id", None) or JobID.nil(),
            "owner_id": getattr(c, "worker_id", None) or
            WorkerID.from_random(),
            "namespace": ns,
            "node_id": node_id_fn(),
        }

    def kv_put(payload) -> bool:
        return kv.put(payload["key"], payload["value"],
                      overwrite=payload.get("overwrite", True))

    def _pin_results(refs, payload):
        """Pin a submission's return objects for the CLIENT, exactly
        like put_object pins puts: the host-side handle is discarded
        when this handler returns, and the client's interest must not
        ride on the handle's destructor losing a race with task
        completion (a release applied after the result lands would
        delete it under the client — observed as ObjectLostError on
        the client's first get).  Scope matches put_object: released
        with the client where the host tracks one (pin_cb +
        worker_id), else held until host shutdown — on that path the
        pre-pin behavior leaked the result BYTES in the memory store
        instead (entry stored after the rc row was already freed, so
        no delete callback could ever fire), so the pin makes an
        existing host-lifetime cost visible rather than adding one."""
        c = core()
        for ref in refs or ():
            c.reference_counter.add_local_ref(ref.object_id())
            if pin_cb is not None and payload.get("worker_id"):
                pin_cb(payload["worker_id"], ref.object_id())

    def submit_task(payload) -> bool:
        _pin_results(core().submit_task(payload["spec"]), payload)
        return True

    def submit_actor_task(payload) -> bool:
        _pin_results(core().submit_actor_task(payload["spec"]), payload)
        return True

    def create_actor(payload) -> bool:
        core().create_actor(payload["spec"],
                            name=payload.get("name", ""),
                            namespace=payload.get("namespace", ""),
                            detached=payload.get("detached", False))
        return True

    def _actor_record(actor):
        if actor is None:
            return None
        return {"actor_id": actor.actor_id,
                "class_name": actor.info().get("class_name", ""),
                "state": actor.state,
                "num_restarts": actor.num_restarts,
                "spec_blob": pickle.dumps(actor.creation_spec, protocol=5)}

    def actor_info(payload):
        return _actor_record(actor_manager().get_actor(payload["actor_id"]))

    def named_actor_info(payload):
        return _actor_record(actor_manager().get_named_actor(
            payload["name"], payload.get("namespace", "")))

    def kill_actor(payload) -> bool:
        actor_manager().destroy_actor(
            payload["actor_id"], no_restart=payload.get("no_restart", True))
        return True

    def put_object(payload):
        value = deserialize(SerializedObject.from_bytes(payload["blob"]))
        c = core()
        ref = c.put(value)
        # Host-side handle drops after this reply; pin through the owner
        # table (scoped per client when the host tracks one, else until
        # host shutdown).
        c.reference_counter.add_local_ref(ref.object_id())
        if pin_cb is not None and payload.get("worker_id"):
            pin_cb(payload["worker_id"], ref.object_id())
        return {"object_id": ref.object_id(), "owner_id": ref.owner_id()}

    def get_value(payload):
        ref = ObjectRef(payload["object_id"], skip_adding_local_ref=True)
        try:
            value = core().get([ref], timeout=payload.get("timeout"))[0]
        except exceptions.GetTimeoutError:
            return None
        except Exception as e:   # noqa: BLE001 — ship the user error
            try:
                return ("error", pickle.dumps(e))
            except Exception:
                return ("error", pickle.dumps(
                    exceptions.RayTpuError(str(e))))
        blob = serialize(value).to_bytes()
        from ray_tpu._private.config import get_config
        if chunk_server is not None and \
                len(blob) > get_config().object_manager_chunk_size:
            meta = chunk_server.open_session(blob)
            if meta is not None:
                return ("chunked", meta)
        return ("ok", blob)

    def wait_refs(payload):
        refs = [ObjectRef(oid, skip_adding_local_ref=True)
                for oid in payload["object_ids"]]
        ready, rest = core().wait(refs,
                                  num_returns=payload.get("num_returns", 1),
                                  timeout=payload.get("timeout"))
        return {"ready": [r.object_id() for r in ready],
                "not_ready": [r.object_id() for r in rest]}

    server.register("runtime_info", runtime_info)
    server.register("kv_put", kv_put)
    server.register("submit_task", submit_task)
    server.register("submit_actor_task", submit_actor_task)
    server.register("create_actor", create_actor)
    server.register("actor_info", actor_info)
    server.register("named_actor_info", named_actor_info)
    server.register("kill_actor", kill_actor)
    server.register("put_object", put_object)
    server.register("get_value", get_value)
    server.register("wait_refs", wait_refs)
