"""Cluster-level scheduling queues + the scheduling tick.

Parity: reference ``src/ray/raylet/scheduling/cluster_task_manager.cc`` —
per-``SchedulingClass`` FIFO queues (:44-123), the periodic
``ScheduleAndDispatchTasks`` tick (also run on every state change,
node_manager.cc:392-394), spillback via ``ScheduleOnNode`` (:285-323),
infeasible queues parked and retried on cluster change (:125-159).

This is the north-star surface (SURVEY.md §3.4): each tick the queues are a
``demand[C, R]`` matrix and the local view an ``avail[N, R]`` matrix.  With
``scheduler_backend=native`` each task is placed by the greedy policy; with
``scheduler_backend=jax`` whole queues are solved in one batched TPU call
(ray_tpu.scheduler.jax_backend) and the per-task grant/spill decisions are
validated against exact fixed-point vectors before commit — stale-view
tolerant, exactly like spillback.
"""

from __future__ import annotations

import logging
import threading
from collections import defaultdict, deque
from typing import Callable, Dict, Tuple

import time

from ray_tpu._private import fault_injection
from ray_tpu._private.config import get_config
from ray_tpu._private.debug import diag_rlock, flight_recorder, loop_only
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.scheduler import policy as policy_mod

logger = logging.getLogger(__name__)

# Consecutive pop->dispatch failures of one task before the lease is
# rejected back to the submitter (which charges the task's retry
# budget) — bounds the requeue loop under a deterministic fault.
_MAX_DISPATCH_REQUEUES = 20

# Tick-latency histogram bounds (seconds).  The north-star budget is
# 50 ms/tick at 1M tasks x 10k nodes (BASELINE.md); the sub-ms buckets
# resolve the common in-process case.
_TICK_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                 0.1, 0.25, 1.0)


class _LeaseBatch:
    """Collector for one batched lease request: N entries, ONE reply.

    Each entry resolves independently (grant when the local dispatch
    path binds a worker, spillback during the scheduling pass, backlog
    when the sweep withdraws it); the batch reply fires once, when the
    last entry lands, carrying the ordered result vector — the
    one-round-trip shape the wire protocol needs."""

    __slots__ = ("results", "_remaining", "_reply", "_lock")

    def __init__(self, n: int, reply: Callable):
        self.results: list = [None] * n
        self._remaining = n
        self._reply = reply
        from ray_tpu._private.debug import diag_lock
        self._lock = diag_lock("_LeaseBatch._lock")

    def resolve(self, idx: int, result: dict) -> None:
        with self._lock:
            if self.results[idx] is not None:
                return          # duplicate resolution: first wins
            self.results[idx] = result
            self._remaining -= 1
            done = self._remaining == 0
        if done:
            # Flight recorder: the grant/backlog vector this batch
            # resolved to — the lease-protocol decision the metrics
            # plane only counts.
            flight_recorder.record(
                "lease.batch_reply", n=len(self.results),
                grants=sum(1 for r in self.results
                           if r and "worker" in r),
                spillbacks=sum(1 for r in self.results
                               if r and "retry_at" in r),
                backlog=sum(1 for r in self.results
                            if r and r.get("backlog")
                            and not r.get("infeasible")),
                infeasible=sum(1 for r in self.results
                               if r and r.get("infeasible")),
                rejected=sum(1 for r in self.results
                             if r and r.get("rejected")))
            self._reply({"results": self.results})


class _BatchEntry:
    """Per-entry reply callable of a :class:`_LeaseBatch` — the queues
    hold ``(spec, reply)`` pairs, and the backlog sweep recognizes batch
    entries by this type to withdraw them."""

    __slots__ = ("batch", "idx")

    def __init__(self, batch: _LeaseBatch, idx: int):
        self.batch = batch
        self.idx = idx

    def __call__(self, result: dict) -> None:
        self.batch.resolve(self.idx, result)


class ClusterTaskManager:
    def __init__(self, raylet):
        self._raylet = raylet
        self._lock = diag_rlock("ClusterTaskManager._lock")
        self._queues: Dict[int, deque] = defaultdict(deque)
        self._infeasible: Dict[int, deque] = defaultdict(deque)
        self._view_version = -1
        self._jax_solver = None
        # Event-driven wakeup coalescing: True while a tick is already
        # scheduled but not yet started — further wakeup requests
        # inside the debounce window fold into it (guarded by _lock).
        self._wakeup_pending = False
        # Lease batches whose unresolved entries the next tick's sweep
        # may withdraw as backlog (guarded by _lock); a batch is swept
        # only by a scheduling pass that STARTED after it was queued.
        self._pending_batches: list = []
        # Tick telemetry: the hot path bumps these plain counters; the
        # scrape-time collector renders them at /metrics (the repo-wide
        # stats pattern — no registry lock on the tick path).  Only the
        # tick-latency histogram observes into the registry directly
        # (bounded _Hist accumulator, one call per tick).
        self._node_label = self._raylet.node_id.hex()[:12]
        self.tick_stats = {"ticks": 0, "busy_ticks": 0,
                           "spillbacks": 0,
                           # Spillbacks decomposed by reason — the two
                           # placement-quality counters the cost-matrix
                           # terms are measured against: no_capacity =
                           # the local node could not run the task now;
                           # locality_override = the cost-aware solve
                           # moved a locally-runnable task to the node
                           # holding its argument bytes / the faster
                           # throughput class.
                           "spillbacks_no_capacity": 0,
                           "spillbacks_locality_override": 0,
                           "jnp_fallbacks": 0,
                           "last_batch_classes": 0, "last_batch_tasks": 0,
                           "dispatch_errors": 0}
        # Consecutive failed dispatch handoffs per task (cleared on
        # success): past _MAX_DISPATCH_REQUEUES the lease is rejected.
        self._dispatch_failures: Dict = {}
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
        label = {"node": self._node_label}

        def _collect(mgr):
            for k, v in mgr.tick_stats.items():
                record_internal(f"ray_tpu.scheduler.tick.{k}", v, **label)
            for k, v in mgr._raylet.lease_stats.items():
                record_internal(f"ray_tpu.scheduler.{k}", v, **label)
            record_internal("ray_tpu.scheduler.pending_queue_depth",
                            mgr.num_queued(), **label)
            # The latency histogram is observed on the tick path, not
            # here — claim its series so it dies with this manager
            # instead of leaking per-node cardinality under churn.
            get_metrics_registry().claim_series(
                "ray_tpu.scheduler.tick_latency", **label)
        get_metrics_registry().register_collector(self, _collect)

    # ---- entry (HandleRequestWorkerLease -> QueueAndScheduleTask) -------
    def queue_and_schedule(self, spec: TaskSpec, reply: Callable):
        with self._lock:
            self._queues[spec.scheduling_class].append((spec, reply))
        self._maybe_prestart(1)
        self.request_tick()

    def queue_and_schedule_batch(self, specs, reply: Callable):
        """Batched lease entry (the dispatch fast path): N same-class
        lease requests in one call, ONE reply carrying the ordered
        grant/spillback/backlog vector.  Entries the first scheduling
        pass can serve resolve through the normal dispatch machinery;
        the pass's leftovers are withdrawn as ``backlog`` (or
        ``infeasible``) by the sweep so the reply is one tick prompt
        instead of deferred until the last worker frees — a deferred
        batch reply would hold granted workers hostage behind entries
        still waiting on the resources those workers occupy."""
        batch = _LeaseBatch(len(specs), reply)
        with self._lock:
            for i, spec in enumerate(specs):
                self._queues[spec.scheduling_class].append(
                    (spec, _BatchEntry(batch, i)))
            self._pending_batches.append(batch)
        self._maybe_prestart(len(specs))
        self.request_tick()

    def requeue_for_spill(self, spec: TaskSpec, reply: Callable):
        """A locally-queued task whose resources vanished (e.g. PG removed)
        goes back through cluster scheduling."""
        with self._lock:
            self._queues[spec.scheduling_class].appendleft((spec, reply))
        self.request_tick()

    def on_resources_freed(self):
        self.request_tick()

    def on_cluster_changed(self):
        """Retry infeasible queues when nodes/resources change (:125-159)."""
        with self._lock:
            for cls, q in self._infeasible.items():
                self._queues[cls].extend(q)
                q.clear()
        self.request_tick()

    def request_tick(self):
        """Event-driven scheduling wakeup, coalesced: the first request
        schedules the tick ``scheduler_wakeup_debounce_ms`` out and
        every further request before it runs folds into it — a
        submission burst becomes ONE batched solve instead of one tick
        per arrival flooding the loop with redundant passes.  The
        periodic ``event_loop_tick_ms`` tick stays as the fallback for
        anything a wakeup edge misses."""
        with self._lock:
            if self._wakeup_pending:
                return
            self._wakeup_pending = True
        debounce = get_config().scheduler_wakeup_debounce_ms / 1000.0
        if debounce > 0:
            self._raylet.loop.schedule_after(
                debounce, self.schedule_and_dispatch, "cluster.schedule")
        else:
            self._raylet.loop.post(self.schedule_and_dispatch,
                                   "cluster.schedule")

    def _maybe_prestart(self, queued_now: int):
        """Predictive warm-worker prestart from queue depth
        (PrestartWorkers parity): fire-and-forget, bounded by
        ``num_prestart_workers``; a no-op when the knob is 0 or the
        pool already has enough idle+starting workers."""
        cfg = get_config()
        if not cfg.num_prestart_workers or not cfg.prestart_on_submit:
            return
        self._raylet.worker_pool.prestart_for_backlog(
            self.num_queued() + queued_now, cfg.num_prestart_workers)

    # ---- the tick -------------------------------------------------------
    @loop_only("raylet")
    def schedule_and_dispatch(self):
        """The scheduling tick.  Loop-affine by design: every caller
        posts it to the raylet loop (queue_and_schedule, resource-freed
        and cluster-changed notifications, the periodic tick) so queue
        pops, the dirty cluster view and tick_stats are only touched
        from one thread — graftcheck R4 verifies the call sites
        statically, the decorator enforces it at runtime in tests."""
        from ray_tpu._private.metrics_agent import observe_internal
        from ray_tpu.util import tracing
        cfg = get_config()
        with self._lock:
            # Requests arriving from here on need a fresh tick.
            self._wakeup_pending = False
            # Sweep set: batches queued BEFORE this pass starts — the
            # pass below definitely considers their entries, so
            # whatever it leaves queued is genuine backlog.  Batches
            # queued mid-pass wait for the next tick.
            sweep, self._pending_batches = self._pending_batches, []
        depth = self._total_queued()
        t0 = time.perf_counter()
        # One span per WORKING tick (idle ticks fire every
        # event_loop_tick_ms — tracing them would bury the timeline).
        span = tracing.span("scheduler.tick", category="sched",
                            node=self._node_label, queued=depth) \
            if depth else None
        try:
            if span is not None:
                span.__enter__()
            if cfg.scheduler_backend == "jax" and depth > 1:
                if self._schedule_batched():
                    return
                # Device path unavailable/invalid this tick — the work
                # was requeued; fall through to the validated native
                # policy.
                self.tick_stats["jnp_fallbacks"] += 1
            self._schedule_greedy()
        finally:
            # Even when the pass raised: an unreplied batch entry left
            # queued would defer the whole batch reply indefinitely.
            self._resolve_batch_backlog(sweep)
            if span is not None:
                span.__exit__(None, None, None)
            dt = time.perf_counter() - t0
            self.tick_stats["ticks"] += 1
            if depth:
                # Flight recorder: one record per WORKING tick — the
                # solve summary (batch shape + spillback split) behind
                # every grant/spill decision this tick made.
                ts = self.tick_stats
                flight_recorder.record(
                    "sched.tick", node=self._node_label, queued=depth,
                    dur_ms=round(dt * 1000.0, 3),
                    batch_tasks=ts["last_batch_tasks"],
                    batch_classes=ts["last_batch_classes"],
                    spillbacks=ts["spillbacks"],
                    no_capacity=ts["spillbacks_no_capacity"],
                    locality_override=ts[
                        "spillbacks_locality_override"],
                    jnp_fallbacks=ts["jnp_fallbacks"],
                    dispatch_errors=ts["dispatch_errors"])
                # Working ticks only (same gate as the span): idle
                # no-op ticks fire every event_loop_tick_ms and their
                # microsecond latencies would drown the signal the
                # 50 ms/tick budget is measured against.
                self.tick_stats["busy_ticks"] += 1
                observe_internal("ray_tpu.scheduler.tick_latency", dt,
                                 buckets=_TICK_BUCKETS,
                                 node=self._node_label)

    def _total_queued(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def _emit_scheduled(self, spec: TaskSpec):
        from ray_tpu.gcs import task_events
        task_events.emit(self._raylet.cluster, spec.task_id,
                         task_events.SCHEDULED,
                         node_id=self._raylet.node_id.hex())

    # A scheduled task is POPPED from its queue before its lease reply
    # fires, so any exception between the pop and the reply silently
    # loses the lease request: the submitter waits forever and the
    # caller's get() times out (the seed-era "lost dispatch" flake —
    # a rare exception on the tick thread, e.g. an import race or IO
    # error deep in a dispatch callback, was swallowed by the event
    # loop WITH the popped work).  Every pop->reply edge below
    # therefore runs through one of these guards, which requeue the
    # task on failure instead of unwinding the tick.

    def _dispatch_local(self, spec: TaskSpec, reply: Callable) -> bool:
        """Hand a locally-scheduled task to the dispatch path.  Returns
        False (never raises) when the handoff failed BEFORE the reply
        was registered — the caller requeues the task and returns its
        resource reservation."""
        try:
            fault_injection.hook("worker.dispatch")
            self._emit_scheduled(spec)
            self._raylet.local_task_manager.queue_and_schedule(spec, reply)
            self._dispatch_failures.pop(spec.task_id, None)
            return True
        except Exception:
            self.tick_stats["dispatch_errors"] += 1
            logger.exception("local dispatch of %s failed; requeueing",
                             spec.task_id)
            return False

    def _spillback_reason(self, spec: TaskSpec, cost_active: bool) -> str:
        """Classify a spillback: ``locality_override`` when a cost-aware
        solve moved a task the LOCAL node could run right now (the
        locality/heterogeneity terms chose a better-placed node);
        ``no_capacity`` otherwise (the local node simply can't take
        it).  Only HYBRID specs ride the cost-aware solve — a policy
        (SPREAD/affinity) spill in the same batch is never an
        override."""
        from ray_tpu.scheduler.policy import SchedulingType
        if not cost_active or spec.scheduling_options.scheduling_type \
                is not SchedulingType.HYBRID:
            return "no_capacity"
        node = self._raylet.cluster_view.node_resources(
            self._raylet.node_id)
        if node is not None and node.is_available(spec.resources):
            return "locality_override"
        return "no_capacity"

    def _reply_spillback(self, spec: TaskSpec, reply: Callable,
                         target, reason: str = "no_capacity") -> None:
        """Deliver a spillback reply; an exception inside the reply
        chain is counted but NOT requeued (the submitter may already
        have acted on it — task-level retries cover the remainder)."""
        try:
            self.tick_stats["spillbacks"] += 1
            self.tick_stats[f"spillbacks_{reason}"] += 1
            flight_recorder.record(
                "sched.spillback", node=self._node_label,
                task=spec.task_id.hex()[:12], reason=reason,
                target=getattr(target, "hex", lambda: str(target))()[:12])
            reply({"retry_at": target})
        except Exception:
            self.tick_stats["dispatch_errors"] += 1
            logger.exception("spillback reply for %s failed",
                             spec.task_id)

    def _requeue(self, spec: TaskSpec, reply: Callable) -> None:
        # Capped: a dispatch path that fails DETERMINISTICALLY (wedged
        # worker pool, persistent fault) must escalate to the submitter
        # as a rejection, not livelock the tick loop in an endless
        # pop -> fail -> requeue -> re-post cycle.
        n = self._dispatch_failures.get(spec.task_id, 0) + 1
        self._dispatch_failures[spec.task_id] = n
        if n > _MAX_DISPATCH_REQUEUES:
            self._dispatch_failures.pop(spec.task_id, None)
            try:
                reply({"rejected": True,
                       "reason": f"local dispatch failed {n} times"})
            except Exception:
                logger.exception("dispatch-failure reply for %s failed",
                                 spec.task_id)
            return
        with self._lock:
            self._queues[spec.scheduling_class].append((spec, reply))
        self.request_tick()

    def _resolve_batch_backlog(self, swept) -> None:
        """Withdraw swept batches' entries the scheduling pass left
        behind: still in ``_queues`` = feasible but no capacity this
        tick (``backlog`` — the submitter keeps the task client-side
        and re-pumps on its next progress edge); parked in
        ``_infeasible`` = no node's totals fit (``infeasible`` — the
        submitter re-leases it through the SINGLE-lease path, which
        parks at the raylet exactly like today so the autoscaler's
        ``resource_load`` demand stays visible until the cluster
        changes)."""
        if not swept:
            return
        swept_set = set(swept)
        withdrawn = []
        with self._lock:
            for queues, infeasible in ((self._queues, False),
                                       (self._infeasible, True)):
                for q in queues.values():
                    if not q:
                        continue
                    kept = [(spec, rep) for spec, rep in q
                            if not (isinstance(rep, _BatchEntry) and
                                    rep.batch in swept_set)]
                    if len(kept) != len(q):
                        withdrawn.extend(
                            (rep, infeasible) for _s, rep in q
                            if isinstance(rep, _BatchEntry) and
                            rep.batch in swept_set)
                        q.clear()
                        q.extend(kept)
        for rep, infeasible in withdrawn:
            result = {"backlog": True}
            if infeasible:
                result["infeasible"] = True
            try:
                rep(result)
            except Exception:
                self.tick_stats["dispatch_errors"] += 1
                logger.exception("batch backlog reply failed")

    def _schedule_greedy(self):
        """Reference-parity greedy loop: per class, per task, pick the best
        node, dispatch locally or spill back."""
        view = self._raylet.cluster_view
        local_id = self._raylet.node_id
        while True:
            progress = False
            with self._lock:
                classes = [c for c, q in self._queues.items() if q]
            for cls in classes:
                while True:
                    with self._lock:
                        q = self._queues[cls]
                        if not q:
                            break
                        spec, reply = q[0]
                    target = policy_mod.schedule(
                        view, spec.resources, spec.scheduling_options,
                        local_node_id=local_id)
                    if target is None:
                        with self._lock:
                            if self._queues[cls] and \
                                    self._queues[cls][0][0] is spec:
                                self._queues[cls].popleft()
                                self._infeasible[cls].append((spec, reply))
                        progress = True
                        continue
                    if target == local_id:
                        # Reserve local resources at decision time (the
                        # view's local row IS the authoritative
                        # NodeResources), then hand to the local dispatch
                        # path; released when the worker lease returns.
                        if not view.subtract(local_id, spec.resources):
                            # Feasible but not currently available: leave
                            # queued; freed resources re-run the tick.
                            break
                        with self._lock:
                            if not (self._queues[cls] and
                                    self._queues[cls][0][0] is spec):
                                view.add_back(local_id, spec.resources)
                                continue
                            self._queues[cls].popleft()
                        if not self._dispatch_local(spec, reply):
                            view.add_back(local_id, spec.resources)
                            self._requeue(spec, reply)
                        progress = True
                    else:
                        if not view.subtract(target, spec.resources):
                            # Stale view: couldn't commit; park and retry.
                            break
                        with self._lock:
                            if not (self._queues[cls] and
                                    self._queues[cls][0][0] is spec):
                                view.add_back(target, spec.resources)
                                continue
                            self._queues[cls].popleft()
                        # Spillback (ScheduleOnNode :285): tell the lessee
                        # to retry at the chosen raylet.  The dirty
                        # subtract above stops this tick from spilling
                        # everything to the same node; the broadcast
                        # corrects it.
                        self._reply_spillback(spec, reply, target)
                        progress = True
            if not progress:
                return

    def _arg_locality_bytes(self, specs) -> Dict:
        """Per-node argument bytes for a class's queued specs — the
        arg-locality cost signal.  Sizes and locations come from the
        object directory (the owner registers both when a big object
        lands in a node store); small inlined args have no directory
        row and correctly contribute nothing — they copy anywhere for
        free.  Called by the device solver only for classes whose specs
        actually carry object-ref args."""
        directory = getattr(self._raylet.cluster, "object_directory", None)
        if directory is None or not hasattr(directory, "size_hint"):
            return {}
        out: Dict = {}
        for spec in specs:
            for oid in spec.arg_object_ids():
                size = directory.size_hint(oid)
                if not size:
                    continue
                for nid in directory.get_locations(oid):
                    out[nid] = out.get(nid, 0) + size
        return out

    def _schedule_batched(self) -> bool:
        """Solve all queues in one device call (scheduler_backend=jax).

        The solver session keeps avail/total device-resident between
        ticks (dirty-row deltas only, ``DeviceRuntimeSolver``); per tick
        only the per-class counts go down and a validated sparse
        assignment comes back.  NOTE the within-bucket fill order
        diverges from the reference's strict min-utilization pick (see
        jax_backend module docstring) — every grant below is still
        re-validated against the exact fixed-point vectors.
        """
        from ray_tpu.scheduler import jax_backend
        if self._jax_solver is None:
            self._jax_solver = jax_backend.DeviceRuntimeSolver(
                node_label=self._raylet.node_id.hex()[:12],
                locality_provider=self._arg_locality_bytes)
        view = self._raylet.cluster_view
        with self._lock:
            work: list = []
            for cls, q in self._queues.items():
                work.extend(q)
                q.clear()
        if not work:
            return True
        self.tick_stats["last_batch_tasks"] = len(work)
        self.tick_stats["last_batch_classes"] = len(
            {spec.scheduling_class for spec, _ in work})
        try:
            assignments = self._jax_solver.solve(
                view, [spec for spec, _ in work])
        except Exception:
            # The solver guards its device path internally, but the
            # whole batch was already POPPED — any escaped exception
            # (e.g. the non-hybrid fallback leg) must not take the
            # popped lease requests down with it.
            logger.exception("batched solve failed; requeueing batch")
            assignments = None
        if assignments is None:
            # Device solve failed — put everything back for greedy.
            with self._lock:
                for spec, reply in work:
                    self._queues[spec.scheduling_class].append((spec, reply))
            return False
        local_id = self._raylet.node_id
        # LOCAL grants commit first (view.subtract), remote spills after:
        # _spillback_reason checks "could the local node still run this
        # task" and must see THIS tick's local reservations, or a batch
        # where cost terms are live would mislabel ordinary
        # capacity-competition spillbacks as locality_override.
        ordered = sorted(
            zip(work, assignments),
            key=lambda wa: 0 if wa[1] == local_id else 1)
        for (spec, reply), target in ordered:
            if target is None:
                # The device solve yields None for can't-place-THIS-TICK,
                # which conflates busy (no availability right now) with
                # structurally infeasible (no node's TOTAL fits).  Only
                # the latter may park in _infeasible — that queue is
                # retried solely on cluster-membership changes, so a
                # merely-busy task parked there stalls until an
                # unrelated broadcast rescues it (or forever).
                feasible_somewhere = view.is_feasible_anywhere(
                    spec.resources)
                with self._lock:
                    if feasible_somewhere:
                        self._queues[spec.scheduling_class].append(
                            (spec, reply))
                    else:
                        self._infeasible[spec.scheduling_class].append(
                            (spec, reply))
            elif target == local_id:
                if not view.subtract(local_id, spec.resources):
                    with self._lock:
                        self._queues[spec.scheduling_class].append(
                            (spec, reply))
                    continue
                if not self._dispatch_local(spec, reply):
                    view.add_back(local_id, spec.resources)
                    self._requeue(spec, reply)
            else:
                # Validate against the exact vectors before committing the
                # spill (kernel output validated by IsSchedulable,
                # SURVEY.md §7.4).
                node = view.node_resources(target)
                if node is not None and node.is_feasible(spec.resources):
                    self._reply_spillback(
                        spec, reply, target,
                        self._spillback_reason(
                            spec, self._jax_solver.last_cost_active))
                else:
                    with self._lock:
                        self._queues[spec.scheduling_class].append(
                            (spec, reply))
        return True

    # ---- introspection --------------------------------------------------
    def num_queued(self) -> int:
        with self._lock:
            return (sum(len(q) for q in self._queues.values()) +
                    sum(len(q) for q in self._infeasible.values()))

    def resource_load(self) -> list:
        """Pending per-task resource demands (queued + infeasible), the
        raylet's contribution to the autoscaler's demand vector
        (reference: ResourcesData.resource_load_by_shape)."""
        with self._lock:
            out = []
            for q in list(self._queues.values()) + \
                    list(self._infeasible.values()):
                out.extend(spec.resources.to_dict() for spec, _ in q)
            return out

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "queued": {c: len(q) for c, q in self._queues.items() if q},
                "infeasible": {c: len(q) for c, q in self._infeasible.items()
                               if q},
            }
