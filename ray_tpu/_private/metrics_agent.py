"""Process-wide metrics registry + Prometheus text exposition.

Parity: the reference's stats pipeline — C++ OpenCensus registry
(``src/ray/stats/metric_defs.h:46-107``) exported through each node's
metrics agent (``python/ray/_private/metrics_agent.py``,
``prometheus_exporter.py``) to a Prometheus scrape endpoint.  Here one
in-process registry serves both internal runtime metrics and the
user-facing ``ray_tpu.util.metrics`` API; the dashboard's ``/metrics``
route renders it in Prometheus text format.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, defaultdict
from typing import Dict, List, Optional, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


class _Hist:
    """Fixed-bucket histogram accumulator: O(buckets) memory however
    many observations land (a per-tick observe must not grow a raw
    observation list forever)."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self, num_buckets: int):
        self.counts = [0] * num_buckets     # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float, buckets) -> None:
        for i, b in enumerate(buckets):
            if value <= b:
                self.counts[i] += 1
                break
        self.sum += value
        self.count += 1


class MetricRecord:
    __slots__ = ("type", "description", "series", "buckets")

    def __init__(self, mtype: str, description: str, buckets=None):
        self.type = mtype
        self.description = description
        # label-tuple -> float (counter/gauge) or list of observations (hist)
        self.series: Dict[_LabelKey, object] = {}
        self.buckets = buckets or []


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, MetricRecord] = {}
        # Scrape-time collectors: (weakref-to-owner, fn).  fn(owner)
        # runs on every exposition and records via record_internal, so
        # hot paths only bump plain counters on their own objects
        # (reference: the metrics agent scrapes component stats
        # periodically instead of locking on every event).
        self._collectors: List = []
        # While a collector fn runs, every series it writes is recorded
        # here (thread-local) so the series can be deleted when the
        # owner dies — otherwise per-worker label cardinality grows
        # without bound under worker churn.
        self._tracking = threading.local()

    def register_collector(self, owner, fn) -> None:
        """Call ``fn(owner)`` at every scrape while ``owner`` is alive;
        the entry — and every series it wrote — drops automatically
        once the owner is collected."""
        import weakref
        with self._lock:
            self._collectors.append((weakref.ref(owner), fn, set()))

    def run_collectors(self) -> None:
        with self._lock:
            entries = list(self._collectors)
        dead = []
        for entry in entries:
            ref, fn, written = entry
            owner = ref()
            if owner is None:
                dead.append(entry)
                continue
            self._tracking.keys = written
            try:
                fn(owner)
            except Exception:
                pass
            finally:
                self._tracking.keys = None
        if dead:
            # Remove ONLY the dead entries: a collector registered
            # while the loop ran (concurrent init vs scrape) must not
            # be lost to a wholesale list replacement.
            with self._lock:
                self._collectors = [c for c in self._collectors
                                    if c not in dead]
                for _ref, _fn, written in dead:
                    for name, labels in written:
                        rec = self._metrics.get(name)
                        if rec is not None:
                            rec.series.pop(labels, None)

    def _note_write(self, name: str, labels: _LabelKey) -> None:
        sink = getattr(self._tracking, "keys", None)
        if sink is not None:
            sink.add((name, labels))

    def claim_series(self, name: str, **labels) -> None:
        """Tie an externally-written series (e.g. a histogram observed
        on a hot path) to the collector currently running, so it is
        pruned with the collector's owner — otherwise per-node series
        written outside collector runs would outlive their node."""
        self._note_write(name, tuple(sorted(labels.items())))

    def register(self, name: str, mtype: str, description: str = "",
                 buckets=None) -> None:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = MetricRecord(mtype, description, buckets)

    def inc(self, name: str, value: float, labels: _LabelKey) -> None:
        self._note_write(name, labels)
        with self._lock:
            rec = self._metrics[name]
            rec.series[labels] = rec.series.get(labels, 0.0) + value

    def set(self, name: str, value: float, labels: _LabelKey) -> None:
        self._note_write(name, labels)
        with self._lock:
            self._metrics[name].series[labels] = value

    def observe(self, name: str, value: float, labels: _LabelKey) -> None:
        self._note_write(name, labels)
        with self._lock:
            rec = self._metrics[name]
            if not rec.buckets:
                # A bucketless histogram used to fall back to a raw
                # observation list — unbounded memory on any hot path
                # that observes forever.  Force the default
                # latency-shaped buckets instead: O(buckets) however
                # many observations land.
                rec.buckets = list(_DEFAULT_BUCKETS)
            h = rec.series.get(labels)
            if not isinstance(h, _Hist):
                h = rec.series[labels] = _Hist(len(rec.buckets))
            h.observe(value, rec.buckets)

    def drop_collector(self, owner) -> None:
        """Remove ``owner``'s collector entry NOW and prune every series
        it ever wrote — the prompt version of the weakref path, for
        owners whose death is an event (node death) rather than a GC."""
        with self._lock:
            doomed = [c for c in self._collectors
                      if c[0]() is owner or c[0]() is None]
            self._collectors = [c for c in self._collectors
                                if c not in doomed]
            for _ref, _fn, written in doomed:
                for name, labels in written:
                    rec = self._metrics.get(name)
                    if rec is not None:
                        rec.series.pop(labels, None)

    def put_series(self, name: str, labels: _LabelKey, value) -> None:
        """Raw series write (float for counter/gauge, :class:`_Hist` for
        histograms) with collector-ownership tracking — the federation
        ingest path writes remote nodes' pre-aggregated series here."""
        self._note_write(name, labels)
        with self._lock:
            rec = self._metrics.get(name)
            if rec is not None:
                rec.series[labels] = value

    def remove_series(self, name: str, labels: _LabelKey) -> None:
        """Drop one series outright (event-driven pruning for series
        written outside any collector's ownership — e.g. a dead node's
        head-local liveness gauge, which would otherwise accumulate
        one permanent label value per dead node under churn)."""
        with self._lock:
            rec = self._metrics.get(name)
            if rec is not None:
                rec.series.pop(labels, None)

    def get_value(self, name: str, labels: _LabelKey = ()):
        with self._lock:
            rec = self._metrics.get(name)
            if rec is None:
                return None
            return rec.series.get(labels)

    def snapshot(self) -> Dict[str, MetricRecord]:
        with self._lock:
            return dict(self._metrics)

    # ---- Prometheus text format ----------------------------------------
    def render_prometheus(self) -> str:
        self.run_collectors()
        out: List[str] = []
        for name, rec in sorted(self.snapshot().items()):
            pname = name.replace(".", "_")
            if rec.description:
                out.append(f"# HELP {pname} {rec.description}")
            ptype = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}[rec.type]
            out.append(f"# TYPE {pname} {ptype}")
            for labels, val in sorted(rec.series.items()):
                lstr = ",".join(f'{k}="{v}"' for k, v in labels)
                lsuf = "{" + lstr + "}" if lstr else ""
                if rec.type == "histogram":
                    if isinstance(val, _Hist):
                        acc = 0
                        for i, b in enumerate(rec.buckets):
                            # Federated accumulators may carry fewer
                            # bucket slots than this record declares.
                            acc += val.counts[i] if i < len(val.counts) \
                                else 0
                            blab = (lstr + "," if lstr else "") \
                                + f'le="{b}"'
                            out.append(f"{pname}_bucket{{{blab}}} {acc}")
                        blab = (lstr + "," if lstr else "") + 'le="+Inf"'
                        out.append(f"{pname}_bucket{{{blab}}} {val.count}")
                        out.append(f"{pname}_sum{lsuf} {val.sum}")
                        out.append(f"{pname}_count{lsuf} {val.count}")
                        continue
                    obs = list(val)
                    acc = 0
                    for b in rec.buckets:
                        acc = sum(1 for o in obs if o <= b)
                        blab = (lstr + "," if lstr else "") + f'le="{b}"'
                        out.append(f"{pname}_bucket{{{blab}}} {acc}")
                    blab = (lstr + "," if lstr else "") + 'le="+Inf"'
                    out.append(f"{pname}_bucket{{{blab}}} {len(obs)}")
                    out.append(f"{pname}_sum{lsuf} {sum(obs)}")
                    out.append(f"{pname}_count{lsuf} {len(obs)}")
                else:
                    out.append(f"{pname}{lsuf} {val}")
        return "\n".join(out) + "\n"


_registry = MetricsRegistry()


def get_metrics_registry() -> MetricsRegistry:
    return _registry


def record_internal(name: str, value: float, mtype: str = "gauge",
                    **labels) -> None:
    """Fire-and-forget internal runtime metric (DECLARE_STATS parity)."""
    _registry.register(name, mtype)
    key = tuple(sorted(labels.items()))
    if mtype == "counter":
        _registry.inc(name, value, key)
    else:
        _registry.set(name, value, key)


# Generic latency-shaped default (seconds): a bucketless histogram
# would fall back to an unbounded raw-observation list.
_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)


def observe_internal(name: str, value: float, buckets=None,
                     **labels) -> None:
    """Fire-and-forget internal histogram observation.  ``buckets`` is
    only honored at first registration (Prometheus semantics: a series'
    buckets never change)."""
    _registry.register(name, "histogram",
                       buckets=buckets or _DEFAULT_BUCKETS)
    _registry.observe(name, value, tuple(sorted(labels.items())))


# ---------------------------------------------------------------------------
# Cluster-wide federation: each node_host ships its registry to the head
# (delta snapshots riding the heartbeat channel); the head merges every
# node's series under a node_id label into ONE exposition at /metrics.
# Parity: the reference's per-node metrics agents all scraped by one
# Prometheus — collapsed here into head-side aggregation because the
# head is the only addressable scrape target in this deployment.
# ---------------------------------------------------------------------------

def _export_value(val) -> object:
    """Wire form of one series value: float, or a plain dict for
    histogram accumulators (no class crosses the wire)."""
    if isinstance(val, _Hist):
        return {"counts": list(val.counts), "sum": val.sum,
                "count": val.count}
    if isinstance(val, list):          # legacy raw-observation list
        return {"counts": [], "sum": float(sum(val)), "count": len(val)}
    return float(val)


class MetricsDeltaShipper:
    """Node-side: snapshot the local registry and diff against the last
    shipped state, returning only series whose value changed — the
    steady-state report for an idle node is empty (``None``).

    Merge semantics head-side are upsert (values are cumulative
    counters / current gauges / cumulative histogram accumulators), so
    a lost report self-heals on the next changed value and a duplicated
    report is idempotent.  Every ``full_every``-th non-empty report is a
    FULL snapshot (resource-broadcaster precedent): the head replaces
    the node's whole entry, so series this registry pruned locally
    (worker churn) stop accumulating head-side — and the ``_last`` diff
    base resets with it, bounding shipper memory the same way."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 full_every: int = 20):
        self._registry = registry or get_metrics_registry()
        self._last: Dict[Tuple[str, _LabelKey], object] = {}
        self._full_every = max(1, full_every)
        self._reports = 0

    def collect_delta(self) -> Tuple[Optional[Dict], bool]:
        """Returns ``(snapshot_or_None, is_full)``."""
        reg = self._registry
        reg.run_collectors()   # fold hot-path counters into the registry
        full = self._reports % self._full_every == 0
        out: Dict[str, dict] = {}
        fresh: Dict[Tuple[str, _LabelKey], object] = {}
        for name, rec in reg.snapshot().items():
            with reg._lock:
                # Series already carrying a node_id label are FEDERATED
                # copies of some other node's data — shipping them again
                # would echo them around the cluster.
                series = {k: _export_value(v)
                          for k, v in rec.series.items()
                          if not any(lk == "node_id" for lk, _ in k)}
                meta = (rec.type, rec.description, list(rec.buckets))
            if full:
                ship = series
                for k, v in series.items():
                    fresh[(name, k)] = v
            else:
                ship = {k: v for k, v in series.items()
                        if self._last.get((name, k)) != v}
                for k, v in ship.items():
                    self._last[(name, k)] = v
            if not ship:
                continue
            out[name] = {"type": meta[0], "description": meta[1],
                         "buckets": meta[2],
                         "series": [[list(k), v]
                                    for k, v in ship.items()]}
        if full:
            self._last = fresh       # drop diff entries for pruned series
        if not out:
            return None, False
        self._reports += 1
        return out, full

    def force_full(self) -> None:
        """A delta's delivery failed (connection bounce, head rejected
        it): the diff base already recorded it as shipped, so a series
        that never changes again would stay stale at the head.  Make
        the NEXT report a full resync instead of waiting out the
        ``full_every`` cycle."""
        self._reports = 0


class _FederatedNode:
    """One remote node's latest shipped series — the OWNER object whose
    lifetime ties the node's series to the registry's collector-pruning
    machinery: while it lives, a scrape-time collector re-writes its
    series (node_id-labelled); dropped on node death, every series it
    wrote is pruned with it."""

    __slots__ = ("node_id", "metrics", "lock", "__weakref__")

    def __init__(self, node_id: str):
        self.node_id = node_id
        # name -> (type, description, buckets, {labels: value})
        self.metrics: Dict[str, tuple] = {}
        self.lock = threading.Lock()


class MetricsFederation:
    """Head-side aggregation: ``ingest`` upserts a node's delta
    snapshot; a per-node collector renders the merged state into the
    head registry at every scrape; ``drop`` prunes a dead node's series
    immediately (and the weakref path covers silent owner loss)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry or get_metrics_registry()
        self._lock = threading.Lock()
        self._nodes: Dict[str, _FederatedNode] = {}
        # Tombstones: a dropped node_id is dead forever (restarted
        # daemons mint fresh node ids), so an in-flight report racing
        # the death-prune must not resurrect the entry into permanent
        # stale gauges.  Bounded ring of recent drops.
        self._dropped: "OrderedDict[str, None]" = OrderedDict()
        self.reports_ingested = 0

    def ingest(self, node_id: str, snapshot: Optional[Dict],
               full: bool = False) -> None:
        if not snapshot:
            return
        stale = None
        with self._lock:
            if node_id in self._dropped:
                return
            entry = self._nodes.get(node_id)
            if full and entry is not None:
                # Full resync REPLACES the node's entry: series the node
                # pruned locally (worker churn) must stop rendering —
                # dropping the old owner prunes everything it ever wrote.
                stale, entry = entry, None
                del self._nodes[node_id]
            if entry is None:
                entry = self._nodes[node_id] = _FederatedNode(node_id)
                self._registry.register_collector(
                    entry,
                    lambda e, _reg=self._registry: _render_node(_reg, e))
            self.reports_ingested += 1
        if stale is not None:
            self._registry.drop_collector(stale)
        with entry.lock:
            for name, rec in snapshot.items():
                cur = entry.metrics.get(name)
                series = dict(cur[3]) if cur is not None else {}
                for labels, value in rec.get("series", ()):
                    series[tuple(tuple(kv) for kv in labels)] = value
                entry.metrics[name] = (rec.get("type", "gauge"),
                                       rec.get("description", ""),
                                       rec.get("buckets") or [],
                                       series)

    def drop(self, node_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(node_id, None)
            self._dropped[node_id] = None
            while len(self._dropped) > 1024:
                self._dropped.popitem(last=False)
        if entry is not None:
            self._registry.drop_collector(entry)

    def revive(self, node_id: str) -> None:
        """Lift a death-prune tombstone: a fenced node RE-REGISTERING
        under the same node id (fresh incarnation) must federate again.
        Safe because admission is now incarnation-gated upstream — only
        a current registration's reports reach ``ingest`` at all."""
        with self._lock:
            self._dropped.pop(node_id, None)

    def node_ids(self) -> List[str]:
        with self._lock:
            return list(self._nodes)


def _render_node(reg: MetricsRegistry, entry: _FederatedNode) -> None:
    """Scrape-time collector body for one federated node: write every
    shipped series into the head registry with the ``node_id`` label
    appended — run inside ``run_collectors`` so each write is tracked
    for pruning."""
    with entry.lock:
        metrics = {name: (m[0], m[1], m[2], dict(m[3]))
                   for name, m in entry.metrics.items()}
    for name, (mtype, desc, buckets, series) in metrics.items():
        reg.register(name, mtype, desc, buckets=buckets or None)
        for labels, value in series.items():
            labeled = tuple(sorted(
                dict(labels, node_id=entry.node_id).items()))
            if isinstance(value, dict):       # histogram accumulator
                h = _Hist(max(len(buckets), len(value.get("counts", ()))))
                h.counts[:len(value.get("counts", ()))] = \
                    value.get("counts", ())
                h.sum = value.get("sum", 0.0)
                h.count = value.get("count", 0)
                reg.put_series(name, labeled, h)
            else:
                reg.put_series(name, labeled, value)
