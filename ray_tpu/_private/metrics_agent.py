"""Process-wide metrics registry + Prometheus text exposition.

Parity: the reference's stats pipeline — C++ OpenCensus registry
(``src/ray/stats/metric_defs.h:46-107``) exported through each node's
metrics agent (``python/ray/_private/metrics_agent.py``,
``prometheus_exporter.py``) to a Prometheus scrape endpoint.  Here one
in-process registry serves both internal runtime metrics and the
user-facing ``ray_tpu.util.metrics`` API; the dashboard's ``/metrics``
route renders it in Prometheus text format.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


class _Hist:
    """Fixed-bucket histogram accumulator: O(buckets) memory however
    many observations land (a per-tick observe must not grow a raw
    observation list forever)."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self, num_buckets: int):
        self.counts = [0] * num_buckets     # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float, buckets) -> None:
        for i, b in enumerate(buckets):
            if value <= b:
                self.counts[i] += 1
                break
        self.sum += value
        self.count += 1


class MetricRecord:
    __slots__ = ("type", "description", "series", "buckets")

    def __init__(self, mtype: str, description: str, buckets=None):
        self.type = mtype
        self.description = description
        # label-tuple -> float (counter/gauge) or list of observations (hist)
        self.series: Dict[_LabelKey, object] = {}
        self.buckets = buckets or []


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, MetricRecord] = {}
        # Scrape-time collectors: (weakref-to-owner, fn).  fn(owner)
        # runs on every exposition and records via record_internal, so
        # hot paths only bump plain counters on their own objects
        # (reference: the metrics agent scrapes component stats
        # periodically instead of locking on every event).
        self._collectors: List = []
        # While a collector fn runs, every series it writes is recorded
        # here (thread-local) so the series can be deleted when the
        # owner dies — otherwise per-worker label cardinality grows
        # without bound under worker churn.
        self._tracking = threading.local()

    def register_collector(self, owner, fn) -> None:
        """Call ``fn(owner)`` at every scrape while ``owner`` is alive;
        the entry — and every series it wrote — drops automatically
        once the owner is collected."""
        import weakref
        with self._lock:
            self._collectors.append((weakref.ref(owner), fn, set()))

    def run_collectors(self) -> None:
        with self._lock:
            entries = list(self._collectors)
        dead = []
        for entry in entries:
            ref, fn, written = entry
            owner = ref()
            if owner is None:
                dead.append(entry)
                continue
            self._tracking.keys = written
            try:
                fn(owner)
            except Exception:
                pass
            finally:
                self._tracking.keys = None
        if dead:
            # Remove ONLY the dead entries: a collector registered
            # while the loop ran (concurrent init vs scrape) must not
            # be lost to a wholesale list replacement.
            with self._lock:
                self._collectors = [c for c in self._collectors
                                    if c not in dead]
                for _ref, _fn, written in dead:
                    for name, labels in written:
                        rec = self._metrics.get(name)
                        if rec is not None:
                            rec.series.pop(labels, None)

    def _note_write(self, name: str, labels: _LabelKey) -> None:
        sink = getattr(self._tracking, "keys", None)
        if sink is not None:
            sink.add((name, labels))

    def claim_series(self, name: str, **labels) -> None:
        """Tie an externally-written series (e.g. a histogram observed
        on a hot path) to the collector currently running, so it is
        pruned with the collector's owner — otherwise per-node series
        written outside collector runs would outlive their node."""
        self._note_write(name, tuple(sorted(labels.items())))

    def register(self, name: str, mtype: str, description: str = "",
                 buckets=None) -> None:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = MetricRecord(mtype, description, buckets)

    def inc(self, name: str, value: float, labels: _LabelKey) -> None:
        self._note_write(name, labels)
        with self._lock:
            rec = self._metrics[name]
            rec.series[labels] = rec.series.get(labels, 0.0) + value

    def set(self, name: str, value: float, labels: _LabelKey) -> None:
        self._note_write(name, labels)
        with self._lock:
            self._metrics[name].series[labels] = value

    def observe(self, name: str, value: float, labels: _LabelKey) -> None:
        self._note_write(name, labels)
        with self._lock:
            rec = self._metrics[name]
            if rec.buckets:
                h = rec.series.get(labels)
                if h is None:
                    h = rec.series[labels] = _Hist(len(rec.buckets))
                h.observe(value, rec.buckets)
            else:
                rec.series.setdefault(labels, []).append(value)

    def get_value(self, name: str, labels: _LabelKey = ()):
        with self._lock:
            rec = self._metrics.get(name)
            if rec is None:
                return None
            return rec.series.get(labels)

    def snapshot(self) -> Dict[str, MetricRecord]:
        with self._lock:
            return dict(self._metrics)

    # ---- Prometheus text format ----------------------------------------
    def render_prometheus(self) -> str:
        self.run_collectors()
        out: List[str] = []
        for name, rec in sorted(self.snapshot().items()):
            pname = name.replace(".", "_")
            if rec.description:
                out.append(f"# HELP {pname} {rec.description}")
            ptype = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}[rec.type]
            out.append(f"# TYPE {pname} {ptype}")
            for labels, val in sorted(rec.series.items()):
                lstr = ",".join(f'{k}="{v}"' for k, v in labels)
                lsuf = "{" + lstr + "}" if lstr else ""
                if rec.type == "histogram":
                    if isinstance(val, _Hist):
                        acc = 0
                        for i, b in enumerate(rec.buckets):
                            acc += val.counts[i]
                            blab = (lstr + "," if lstr else "") \
                                + f'le="{b}"'
                            out.append(f"{pname}_bucket{{{blab}}} {acc}")
                        blab = (lstr + "," if lstr else "") + 'le="+Inf"'
                        out.append(f"{pname}_bucket{{{blab}}} {val.count}")
                        out.append(f"{pname}_sum{lsuf} {val.sum}")
                        out.append(f"{pname}_count{lsuf} {val.count}")
                        continue
                    obs = list(val)
                    acc = 0
                    for b in rec.buckets:
                        acc = sum(1 for o in obs if o <= b)
                        blab = (lstr + "," if lstr else "") + f'le="{b}"'
                        out.append(f"{pname}_bucket{{{blab}}} {acc}")
                    blab = (lstr + "," if lstr else "") + 'le="+Inf"'
                    out.append(f"{pname}_bucket{{{blab}}} {len(obs)}")
                    out.append(f"{pname}_sum{lsuf} {sum(obs)}")
                    out.append(f"{pname}_count{lsuf} {len(obs)}")
                else:
                    out.append(f"{pname}{lsuf} {val}")
        return "\n".join(out) + "\n"


_registry = MetricsRegistry()


def get_metrics_registry() -> MetricsRegistry:
    return _registry


def record_internal(name: str, value: float, mtype: str = "gauge",
                    **labels) -> None:
    """Fire-and-forget internal runtime metric (DECLARE_STATS parity)."""
    _registry.register(name, mtype)
    key = tuple(sorted(labels.items()))
    if mtype == "counter":
        _registry.inc(name, value, key)
    else:
        _registry.set(name, value, key)


# Generic latency-shaped default (seconds): a bucketless histogram
# would fall back to an unbounded raw-observation list.
_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)


def observe_internal(name: str, value: float, buckets=None,
                     **labels) -> None:
    """Fire-and-forget internal histogram observation.  ``buckets`` is
    only honored at first registration (Prometheus semantics: a series'
    buckets never change)."""
    _registry.register(name, "histogram",
                       buckets=buckets or _DEFAULT_BUCKETS)
    _registry.observe(name, value, tuple(sorted(labels.items())))
