"""Distributed reference counting for objects.

Parity target: reference ``src/ray/core_worker/reference_count.h:61`` —
per-owner counts of (a) local Python refs, (b) refs held by submitted pending
tasks, (c) borrowers, (d) nested objects contained in still-live outer
objects, plus lineage pinning so a freed-but-reconstructable object's creating
task spec is retained.

The reference implementation is 1,480 LoC of distributed edge cases because
borrower sets are reconciled over RPC.  In this runtime the owner's table is
authoritative in-process and borrower registration is a direct call, so the
protocol collapses to a single table — but the *semantics* (an object is
freeable only when local + submitted-task + borrower + contained counts are
all zero) are identical and tested identically.

Lock striping (PR 13's contention profiler attributed ~31 ms of sampled
wait per 500-task burst to the single ``ReferenceCounter._lock``): the
object-id table is striped 16-way by ``hash(object_id)`` — consistent
with ``shm_store.cpp``'s striped object-table locks — so concurrent
put/release/borrow traffic on distinct objects never contends.  The
discipline is **at most one stripe lock held at a time**: mutators take
only the target object's stripe lock; the out-of-scope cascade
(``contains``/``contained_in`` edges cross stripes) is an iterative
worklist that re-acquires each inner object's stripe lock one at a
time, and delete callbacks run with NO stripe lock held (they re-enter
the store/lineage layers).  Every stripe keeps witness + contention
instrumentation under its own ``ReferenceCounter._lock[sNN]`` name.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, List, Optional, Set

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.debug import diag_condition, diag_rlock, swallow


class Reference:
    __slots__ = ("local_refs", "submitted_task_refs", "borrowers",
                 "contained_in", "contains", "owned", "lineage_task_id",
                 "on_delete", "pinned_node", "spilled_url", "out_of_scope")

    def __init__(self, owned: bool = True):
        self.local_refs = 0
        self.submitted_task_refs = 0
        self.borrowers: Set = set()
        # Outer object ids whose values contain this object id.
        self.contained_in: Set[ObjectID] = set()
        self.contains: Set[ObjectID] = set()
        self.owned = owned
        # Task that created this object — retained while reconstruction is
        # possible (lineage pinning, ray_config_def.h:97).
        self.lineage_task_id: Optional[TaskID] = None
        self.on_delete: List[Callable[[ObjectID], None]] = []
        self.pinned_node = None
        self.spilled_url: Optional[str] = None
        self.out_of_scope = False

    def total(self) -> int:
        return (self.local_refs + self.submitted_task_refs +
                len(self.borrowers) + len(self.contained_in))


#: Stripe count for the object-id table (power of two so the selector is
#: a mask; 16-way matches shm_store.cpp's striped object-table locks).
_NUM_STRIPES = 16


class _RefStripe:
    """One lock-striped shard of the reference table."""

    __slots__ = ("lock", "refs")

    def __init__(self, index: int):
        self.lock = diag_rlock(f"ReferenceCounter._lock[s{index:02d}]")
        self.refs: Dict[ObjectID, Reference] = {}


class ReferenceCounter:
    def __init__(self):
        self._stripes = [_RefStripe(i) for i in range(_NUM_STRIPES)]
        # Subscribers list has its own (tiny, uncontended) lock so the
        # cascade can snapshot it without holding any stripe lock.
        self._subs_lock = diag_rlock("ReferenceCounter._subs_lock")
        self._delete_subscribers: List[Callable[[ObjectID], None]] = []
        # Destructor-context releases (release_local_ref_async): an
        # ObjectRef.__del__ can fire from GC at ANY allocation point —
        # including inside a store-lock or task-manager-lock region of
        # the interrupted thread.  Running the out-of-scope cascade
        # (store delete, lineage eviction) inline there nests those
        # locks in arbitrary orders; the lock-order witness caught a
        # real MemoryStore<->TaskManager ABBA formed exactly this way.
        # Instead, __del__ only enqueues; a dedicated drain thread (or
        # a query API needing the settled state) runs the release from
        # a clean, no-locks-held context.  (Reference parity: Ray's
        # dtor hands RemoveLocalReference to the core worker's
        # io_service rather than running deletion in the GC context.)
        self._release_queue: "collections.deque[ObjectID]" = \
            collections.deque()
        self._release_cv = diag_condition(
            name="ReferenceCounter._release_cv")
        self._release_thread: Optional[threading.Thread] = None
        #: Releases the drain thread has popped but not yet applied —
        #: flush must wait these out or a query could read stale state
        #: (queue empty != queue settled).
        self._release_inflight = 0
        self._closed = False

    def _stripe(self, object_id: ObjectID) -> _RefStripe:
        return self._stripes[hash(object_id) & (_NUM_STRIPES - 1)]

    # ---- registration ---------------------------------------------------
    def add_owned_object(self, object_id: ObjectID,
                        lineage_task_id: Optional[TaskID] = None,
                        contained_ids: Optional[List[ObjectID]] = None):
        # Inner ``contained_in`` edges go in FIRST (each under its own
        # stripe lock) so the inner objects are pinned before the outer
        # ref's ``contains`` set becomes visible — the cascade never
        # finds a contains edge whose reverse edge is missing.
        for inner in contained_ids or []:
            istripe = self._stripe(inner)
            with istripe.lock:
                inner_ref = istripe.refs.setdefault(
                    inner, Reference(owned=False))
                inner_ref.contained_in.add(object_id)
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.setdefault(object_id, Reference(owned=True))
            ref.owned = True
            ref.lineage_task_id = lineage_task_id
            for inner in contained_ids or []:
                ref.contains.add(inner)

    def add_borrowed_object(self, object_id: ObjectID, borrower) -> None:
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.setdefault(object_id, Reference(owned=False))
            ref.borrowers.add(borrower)

    def remove_borrower(self, object_id: ObjectID, borrower) -> None:
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            if ref is None:
                return
            ref.borrowers.discard(borrower)
            item = self._maybe_delete_locked(stripe, object_id)
        if item is not None:
            self._run_delete_cascade(item)

    # ---- local refs (ObjectRef ctor/dtor) -------------------------------
    def add_local_ref(self, object_id: ObjectID):
        stripe = self._stripe(object_id)
        with stripe.lock:
            stripe.refs.setdefault(object_id, Reference()).local_refs += 1

    def remove_local_ref(self, object_id: ObjectID):
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            if ref is None:
                return
            # Floored: a duplicate decrement must degrade to a leak, not
            # a negative count that cancels out refs someone else holds
            # and frees the object under them.
            ref.local_refs = max(0, ref.local_refs - 1)
            item = self._maybe_delete_locked(stripe, object_id)
        if item is not None:
            self._run_delete_cascade(item)

    def release_local_ref_async(self, object_id: ObjectID):
        """Destructor-safe local-ref release: enqueue only, never run
        the out-of-scope cascade in the caller's (GC-interrupted) lock
        context.  The drain thread — or the next settled-state query —
        performs the actual :meth:`remove_local_ref`.

        After :meth:`close` (shutdown teardown, nothing left to race)
        the release applies inline — a dead drain thread must not turn
        late destructors into silent leaks."""
        with self._release_cv:
            if not self._closed:
                self._release_queue.append(object_id)
                if self._release_thread is None:
                    self._release_thread = threading.Thread(
                        target=self._release_loop, daemon=True,
                        name="ray_tpu::ref_release")
                    self._release_thread.start()
                self._release_cv.notify()
                return
        self.remove_local_ref(object_id)

    def flush_pending_releases(self):
        """Apply queued destructor releases NOW, in the calling thread
        (which, unlike a ``__del__`` context, holds no runtime locks),
        and wait out any release the drain thread has in flight.  Query
        APIs call this so ``del ref; gc.collect()`` is observably
        synchronous, exactly as the inline destructor was."""
        while True:
            with self._release_cv:
                if not self._release_queue:
                    # Queue empty is not settled: the drain may have
                    # popped an oid it hasn't applied yet.
                    while self._release_inflight:
                        self._release_cv.wait(timeout=0.1)
                    return
                oid = self._release_queue.popleft()
            self.remove_local_ref(oid)

    def _release_loop(self):
        while True:
            with self._release_cv:
                while not self._release_queue and not self._closed:
                    self._release_cv.wait(timeout=0.5)
                if not self._release_queue:
                    if self._closed:
                        return
                    continue
                oid = self._release_queue.popleft()
                self._release_inflight += 1
            try:
                self.remove_local_ref(oid)
            except Exception as e:
                swallow.noted("reference_counter.release", e)
            finally:
                with self._release_cv:
                    self._release_inflight -= 1
                    self._release_cv.notify_all()

    def close(self):
        """Stop the drain thread (cluster shutdown); pending releases
        are applied inline first so nothing leaks silently."""
        self.flush_pending_releases()
        with self._release_cv:
            self._closed = True
            self._release_cv.notify_all()
        t = self._release_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)

    # ---- task-arg refs --------------------------------------------------
    def add_submitted_task_refs(self, object_ids: List[ObjectID]):
        for oid in object_ids:
            stripe = self._stripe(oid)
            with stripe.lock:
                stripe.refs.setdefault(
                    oid, Reference()).submitted_task_refs += 1

    def remove_submitted_task_refs(self, object_ids: List[ObjectID]):
        for oid in object_ids:
            stripe = self._stripe(oid)
            item = None
            with stripe.lock:
                ref = stripe.refs.get(oid)
                if ref is None:
                    continue
                ref.submitted_task_refs = max(
                    0, ref.submitted_task_refs - 1)
                item = self._maybe_delete_locked(stripe, oid)
            if item is not None:
                self._run_delete_cascade(item)

    # ---- queries --------------------------------------------------------
    # Queries settle pending destructor releases first: a test's
    # `del ref; gc.collect(); assert not has_reference(...)` must see
    # the release applied, and the flushing thread is a clean (no
    # runtime locks held) context to run the cascade from.
    def has_reference(self, object_id: ObjectID) -> bool:
        self.flush_pending_releases()
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            return ref is not None and not ref.out_of_scope

    def ref_count(self, object_id: ObjectID) -> int:
        self.flush_pending_releases()
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            return 0 if ref is None or ref.out_of_scope else ref.total()

    def lineage_task(self, object_id: ObjectID) -> Optional[TaskID]:
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            return ref.lineage_task_id if ref else None

    def num_tracked(self) -> int:
        # One stripe lock at a time; the sum is a point-in-time
        # approximation under concurrent churn, exact when quiescent
        # (which is when tests assert on it).
        total = 0
        for stripe in self._stripes:
            with stripe.lock:
                total += sum(
                    1 for r in stripe.refs.values() if not r.out_of_scope)
        return total

    def set_pinned_node(self, object_id: ObjectID, node_id):
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            if ref is not None:
                ref.pinned_node = node_id

    def describe(self, object_id: ObjectID) -> Optional[dict]:
        """Debug/error-context snapshot of one reference (ownership,
        counts, pinned node, spill record) — feeds the actionable
        ObjectLostError message."""
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            if ref is None:
                return None
            return {
                "owned": ref.owned,
                "local_refs": ref.local_refs,
                "submitted_task_refs": ref.submitted_task_refs,
                "borrowers": len(ref.borrowers),
                "pinned_node": ref.pinned_node,
                "spilled_url": ref.spilled_url,
                "out_of_scope": ref.out_of_scope,
            }

    def set_spilled_url(self, object_id: ObjectID, url: str):
        stripe = self._stripe(object_id)
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            if ref is not None:
                ref.spilled_url = url

    # ---- deletion -------------------------------------------------------
    def subscribe_deleted(self, cb: Callable[[ObjectID], None]):
        """Register a callback fired when an object goes out of scope
        (the object store uses this to evict the value)."""
        with self._subs_lock:
            self._delete_subscribers.append(cb)

    def add_on_delete(self, object_id: ObjectID, cb: Callable[[ObjectID], None]):
        stripe = self._stripe(object_id)
        fire = False
        with stripe.lock:
            ref = stripe.refs.get(object_id)
            if ref is None or ref.out_of_scope:
                fire = True
            else:
                ref.on_delete.append(cb)
        if fire:
            cb(object_id)

    def _maybe_delete_locked(self, stripe: _RefStripe,
                             object_id: ObjectID):
        """Out-of-scope check for ``object_id``; must hold
        ``stripe.lock`` (the stripe owning ``object_id``).  Removes the
        ref from the table and returns a ``(object_id, on_delete,
        contains)`` work item for :meth:`_run_delete_cascade`, or
        ``None`` if the object stays live.  Callbacks and cross-stripe
        edge removal are deliberately NOT done here — they need other
        stripes' locks (or none)."""
        ref = stripe.refs.get(object_id)
        if ref is None or ref.out_of_scope or ref.total() > 0:
            return None
        ref.out_of_scope = True
        del stripe.refs[object_id]
        return (object_id, ref.on_delete, ref.contains)

    def _run_delete_cascade(self, item) -> None:
        """Run the out-of-scope cascade for one freed object, holding
        at most one stripe lock at any instant.  Releasing an outer
        object releases the ``contained_in`` edges of its inner objects
        — possibly cascading (reference: nested refs) — via an
        iterative worklist (outer's callbacks fire before its inners').
        Delete callbacks run with NO stripe lock held: they re-enter
        store/lineage layers and must not create lock-order edges."""
        worklist = collections.deque([item])
        while worklist:
            object_id, on_delete, contains = worklist.popleft()
            for inner in contains:
                istripe = self._stripe(inner)
                inner_item = None
                with istripe.lock:
                    inner_ref = istripe.refs.get(inner)
                    if inner_ref is not None:
                        inner_ref.contained_in.discard(object_id)
                        inner_item = self._maybe_delete_locked(
                            istripe, inner)
                if inner_item is not None:
                    worklist.append(inner_item)
            with self._subs_lock:
                callbacks = list(on_delete) + list(self._delete_subscribers)
            for cb in callbacks:
                try:
                    cb(object_id)
                except Exception as e:
                    # A failed delete subscriber silently leaks its copy
                    # of the object — count it (graftcheck R7 fan-out
                    # rule).
                    swallow.noted("refcount.delete_subscriber", e)
