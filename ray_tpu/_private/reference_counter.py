"""Distributed reference counting for objects.

Parity target: reference ``src/ray/core_worker/reference_count.h:61`` —
per-owner counts of (a) local Python refs, (b) refs held by submitted pending
tasks, (c) borrowers, (d) nested objects contained in still-live outer
objects, plus lineage pinning so a freed-but-reconstructable object's creating
task spec is retained.

The reference implementation is 1,480 LoC of distributed edge cases because
borrower sets are reconciled over RPC.  In this runtime the owner's table is
authoritative in-process and borrower registration is a direct call, so the
protocol collapses to a single table — but the *semantics* (an object is
freeable only when local + submitted-task + borrower + contained counts are
all zero) are identical and tested identically.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, List, Optional, Set

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.debug import diag_condition, diag_rlock, swallow


class Reference:
    __slots__ = ("local_refs", "submitted_task_refs", "borrowers",
                 "contained_in", "contains", "owned", "lineage_task_id",
                 "on_delete", "pinned_node", "spilled_url", "out_of_scope")

    def __init__(self, owned: bool = True):
        self.local_refs = 0
        self.submitted_task_refs = 0
        self.borrowers: Set = set()
        # Outer object ids whose values contain this object id.
        self.contained_in: Set[ObjectID] = set()
        self.contains: Set[ObjectID] = set()
        self.owned = owned
        # Task that created this object — retained while reconstruction is
        # possible (lineage pinning, ray_config_def.h:97).
        self.lineage_task_id: Optional[TaskID] = None
        self.on_delete: List[Callable[[ObjectID], None]] = []
        self.pinned_node = None
        self.spilled_url: Optional[str] = None
        self.out_of_scope = False

    def total(self) -> int:
        return (self.local_refs + self.submitted_task_refs +
                len(self.borrowers) + len(self.contained_in))


class ReferenceCounter:
    def __init__(self):
        self._lock = diag_rlock("ReferenceCounter._lock")
        self._refs: Dict[ObjectID, Reference] = {}
        self._delete_subscribers: List[Callable[[ObjectID], None]] = []
        # Destructor-context releases (release_local_ref_async): an
        # ObjectRef.__del__ can fire from GC at ANY allocation point —
        # including inside a store-lock or task-manager-lock region of
        # the interrupted thread.  Running the out-of-scope cascade
        # (store delete, lineage eviction) inline there nests those
        # locks in arbitrary orders; the lock-order witness caught a
        # real MemoryStore<->TaskManager ABBA formed exactly this way.
        # Instead, __del__ only enqueues; a dedicated drain thread (or
        # a query API needing the settled state) runs the release from
        # a clean, no-locks-held context.  (Reference parity: Ray's
        # dtor hands RemoveLocalReference to the core worker's
        # io_service rather than running deletion in the GC context.)
        self._release_queue: "collections.deque[ObjectID]" = \
            collections.deque()
        self._release_cv = diag_condition(
            name="ReferenceCounter._release_cv")
        self._release_thread: Optional[threading.Thread] = None
        #: Releases the drain thread has popped but not yet applied —
        #: flush must wait these out or a query could read stale state
        #: (queue empty != queue settled).
        self._release_inflight = 0
        self._closed = False

    # ---- registration ---------------------------------------------------
    def add_owned_object(self, object_id: ObjectID,
                        lineage_task_id: Optional[TaskID] = None,
                        contained_ids: Optional[List[ObjectID]] = None):
        with self._lock:
            ref = self._refs.setdefault(object_id, Reference(owned=True))
            ref.owned = True
            ref.lineage_task_id = lineage_task_id
            for inner in contained_ids or []:
                ref.contains.add(inner)
                inner_ref = self._refs.setdefault(inner, Reference(owned=False))
                inner_ref.contained_in.add(object_id)

    def add_borrowed_object(self, object_id: ObjectID, borrower) -> None:
        with self._lock:
            ref = self._refs.setdefault(object_id, Reference(owned=False))
            ref.borrowers.add(borrower)

    def remove_borrower(self, object_id: ObjectID, borrower) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.borrowers.discard(borrower)
            self._maybe_delete(object_id)

    # ---- local refs (ObjectRef ctor/dtor) -------------------------------
    def add_local_ref(self, object_id: ObjectID):
        with self._lock:
            self._refs.setdefault(object_id, Reference()).local_refs += 1

    def remove_local_ref(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            # Floored: a duplicate decrement must degrade to a leak, not
            # a negative count that cancels out refs someone else holds
            # and frees the object under them.
            ref.local_refs = max(0, ref.local_refs - 1)
            self._maybe_delete(object_id)

    def release_local_ref_async(self, object_id: ObjectID):
        """Destructor-safe local-ref release: enqueue only, never run
        the out-of-scope cascade in the caller's (GC-interrupted) lock
        context.  The drain thread — or the next settled-state query —
        performs the actual :meth:`remove_local_ref`.

        After :meth:`close` (shutdown teardown, nothing left to race)
        the release applies inline — a dead drain thread must not turn
        late destructors into silent leaks."""
        with self._release_cv:
            if not self._closed:
                self._release_queue.append(object_id)
                if self._release_thread is None:
                    self._release_thread = threading.Thread(
                        target=self._release_loop, daemon=True,
                        name="ray_tpu::ref_release")
                    self._release_thread.start()
                self._release_cv.notify()
                return
        self.remove_local_ref(object_id)

    def flush_pending_releases(self):
        """Apply queued destructor releases NOW, in the calling thread
        (which, unlike a ``__del__`` context, holds no runtime locks),
        and wait out any release the drain thread has in flight.  Query
        APIs call this so ``del ref; gc.collect()`` is observably
        synchronous, exactly as the inline destructor was."""
        while True:
            with self._release_cv:
                if not self._release_queue:
                    # Queue empty is not settled: the drain may have
                    # popped an oid it hasn't applied yet.
                    while self._release_inflight:
                        self._release_cv.wait(timeout=0.1)
                    return
                oid = self._release_queue.popleft()
            self.remove_local_ref(oid)

    def _release_loop(self):
        while True:
            with self._release_cv:
                while not self._release_queue and not self._closed:
                    self._release_cv.wait(timeout=0.5)
                if not self._release_queue:
                    if self._closed:
                        return
                    continue
                oid = self._release_queue.popleft()
                self._release_inflight += 1
            try:
                self.remove_local_ref(oid)
            except Exception as e:
                swallow.noted("reference_counter.release", e)
            finally:
                with self._release_cv:
                    self._release_inflight -= 1
                    self._release_cv.notify_all()

    def close(self):
        """Stop the drain thread (cluster shutdown); pending releases
        are applied inline first so nothing leaks silently."""
        self.flush_pending_releases()
        with self._release_cv:
            self._closed = True
            self._release_cv.notify_all()
        t = self._release_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)

    # ---- task-arg refs --------------------------------------------------
    def add_submitted_task_refs(self, object_ids: List[ObjectID]):
        with self._lock:
            for oid in object_ids:
                self._refs.setdefault(oid, Reference()).submitted_task_refs += 1

    def remove_submitted_task_refs(self, object_ids: List[ObjectID]):
        with self._lock:
            for oid in object_ids:
                ref = self._refs.get(oid)
                if ref is None:
                    continue
                ref.submitted_task_refs = max(0, ref.submitted_task_refs - 1)
                self._maybe_delete(oid)

    # ---- queries --------------------------------------------------------
    # Queries settle pending destructor releases first: a test's
    # `del ref; gc.collect(); assert not has_reference(...)` must see
    # the release applied, and the flushing thread is a clean (no
    # runtime locks held) context to run the cascade from.
    def has_reference(self, object_id: ObjectID) -> bool:
        self.flush_pending_releases()
        with self._lock:
            ref = self._refs.get(object_id)
            return ref is not None and not ref.out_of_scope

    def ref_count(self, object_id: ObjectID) -> int:
        self.flush_pending_releases()
        with self._lock:
            ref = self._refs.get(object_id)
            return 0 if ref is None or ref.out_of_scope else ref.total()

    def lineage_task(self, object_id: ObjectID) -> Optional[TaskID]:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.lineage_task_id if ref else None

    def num_tracked(self) -> int:
        with self._lock:
            return sum(1 for r in self._refs.values() if not r.out_of_scope)

    def set_pinned_node(self, object_id: ObjectID, node_id):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.pinned_node = node_id

    def describe(self, object_id: ObjectID) -> Optional[dict]:
        """Debug/error-context snapshot of one reference (ownership,
        counts, pinned node, spill record) — feeds the actionable
        ObjectLostError message."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return None
            return {
                "owned": ref.owned,
                "local_refs": ref.local_refs,
                "submitted_task_refs": ref.submitted_task_refs,
                "borrowers": len(ref.borrowers),
                "pinned_node": ref.pinned_node,
                "spilled_url": ref.spilled_url,
                "out_of_scope": ref.out_of_scope,
            }

    def set_spilled_url(self, object_id: ObjectID, url: str):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.spilled_url = url

    # ---- deletion -------------------------------------------------------
    def subscribe_deleted(self, cb: Callable[[ObjectID], None]):
        """Register a callback fired when an object goes out of scope
        (the object store uses this to evict the value)."""
        with self._lock:
            self._delete_subscribers.append(cb)

    def add_on_delete(self, object_id: ObjectID, cb: Callable[[ObjectID], None]):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None or ref.out_of_scope:
                cb(object_id)
            else:
                ref.on_delete.append(cb)

    def _maybe_delete(self, object_id: ObjectID):
        # Must hold self._lock.
        ref = self._refs.get(object_id)
        if ref is None or ref.out_of_scope or ref.total() > 0:
            return
        ref.out_of_scope = True
        # Releasing an outer object releases the contained-in edges of its
        # inner objects — possibly cascading (reference: nested refs).
        for inner in ref.contains:
            inner_ref = self._refs.get(inner)
            if inner_ref is not None:
                inner_ref.contained_in.discard(object_id)
                self._maybe_delete(inner)
        callbacks = ref.on_delete + self._delete_subscribers
        del self._refs[object_id]
        for cb in callbacks:
            try:
                cb(object_id)
            except Exception as e:
                # A failed delete subscriber silently leaks its copy of
                # the object — count it (graftcheck R7 fan-out rule).
                from ray_tpu._private.debug import swallow
                swallow.noted("refcount.delete_subscriber", e)
