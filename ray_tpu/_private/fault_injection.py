"""Deterministic fault injection for chaos testing.

Parity: the reference's chaos tooling is probabilistic (``test_chaos.py``
``NodeKillerActor`` kills a random node every N seconds) which makes
failures unreproducible under CI load.  Here failure POINTS are named
call sites compiled into the runtime — each ``hook(point)`` is a no-op
until a test arms it — and arming is count-based, not random, so a test
that says "fail the first two spill writes" fails exactly those two,
every run, on every machine.

Named points wired into the runtime (grep ``fault_injection.hook``):

========================  ====================================================
``spill.write``           before a spill batch is written to disk
``restore.read``          before a spilled object is read back
``transfer.chunk``        per received chunk of a streamed object transfer
``transfer.relay``        per relay read served from an IN-FLIGHT transfer's
                          assembled prefix (chunk relay, sender side)
``node.heartbeat``        before a raylet sends its GCS heartbeat
``worker.dispatch``       before a scheduled task is handed to local dispatch
``worker.lease_batch``    before a batched lease request enters scheduling
``loop.stall``            before an EventLoop executes a handler (delay mode
                          wedges the loop — the stall-watchdog drill)
``lock.hold``             after a diag lock is acquired (delay mode extends
                          the hold — attributable contention for the
                          profiling plane; only fires on witness/contention
                          wrapped locks)
``rpc.send``              client side, before a framed request leaves the
                          process (ctx: verb, peer, peer_host, peer_port)
``rpc.recv``              server side, before an inbound request dispatches
                          (ctx: verb, peer, peer_host, peer_port)
``serve.request``         serve router, before a request is dispatched to a
                          replica (ctx: deployment; ``error`` surfaces to
                          the client attributed, ``delay`` slows dispatch,
                          ``drop`` loses the dispatch in flight — the
                          router re-assigns it)
========================  ====================================================

Modes:

* ``error``     — raise :class:`FaultInjectedError` at the hook;
* ``delay``     — sleep ``delay_s`` at the hook (slow-IO / slow-network);
* ``kill``      — ``SIGKILL`` the calling process (real process death; for
  node-host / worker OS processes);
* ``drop``      — the hook RETURNS ``"drop"`` and the call site discards
  the message (wire fault points only: a dropped send never leaves the
  process, a dropped recv never dispatches — the asymmetric-partition
  primitive);
* ``duplicate`` — the hook returns ``"duplicate"`` and the call site
  delivers the message twice (duplicate-delivery chaos; the RPC dedup
  window is what must make it harmless).

Armings can be SCOPED with a ``match`` dict compared against the
``hook`` call's keyword context via :func:`fnmatch.fnmatchcase` — e.g.
``arm("rpc.send", "drop", count=-1, match={"verb": "heartbeat"})`` drops
only heartbeats, ``match={"peer": "127.0.0.1:6200"}`` drops only frames
to one address.  Several differently-scoped armings may coexist on one
point; the first match (arming order) wins.

Arming is in-process via :func:`arm` or cross-process via the
``RAY_TPU_FAULT_POINTS`` env var (parsed at import in every daemon):

    RAY_TPU_FAULT_POINTS="spill.write:error:2,rpc.send@verb=heartbeat:drop:-1"

format per entry: ``point[@k=v[&k=v...]]:mode[:count[:delay_s]]``
(count -1 = every hit; match values must avoid ``:``/``,``/``&`` —
address-scoped armings go through :func:`arm` or the ``arm_fault`` wire
verb instead).  Malformed entries are skipped, never fatal: this parses
at import time in every daemon, and a typo in an env var must not take
the cluster down.

Spawned daemons additionally expose ``arm_fault`` / ``disarm_fault``
RPC verbs for post-startup arming; those verbs are EXEMPT from the wire
fault points themselves (``rpc`` module ``_CONTROL_VERBS``) so an armed
partition can always be healed through it — that is what
:class:`partition` builds on.
"""

from __future__ import annotations

import fnmatch
import os
import threading
import time
from typing import Dict, List, Optional

from ray_tpu import exceptions


class FaultInjectedError(exceptions.RayTpuError):
    """Raised by an armed ``error``-mode failure point."""

    def __init__(self, point: str):
        self.point = point
        super().__init__(f"injected fault at {point!r}")


_MODES = ("error", "delay", "kill", "drop", "duplicate")


class _Arming:
    __slots__ = ("mode", "remaining", "skip", "delay_s", "fired", "match")

    def __init__(self, mode: str, count: int, skip: int, delay_s: float,
                 match: Optional[dict]):
        self.mode = mode
        self.remaining = count     # -1 = unlimited
        self.skip = skip           # let the first N hits through
        self.delay_s = delay_s
        self.match = dict(match) if match else None
        self.fired = 0


def _ctx_matches(match: Optional[dict], ctx: dict) -> bool:
    if not match:
        return True
    for key, pattern in match.items():
        value = ctx.get(key)
        if value is None or not fnmatch.fnmatchcase(str(value),
                                                    str(pattern)):
            return False
    return True


_lock = threading.Lock()
_points: Dict[str, List[_Arming]] = {}
#: Total hits per point since arming began (kept after disarm so tests
#: can assert "the fault actually fired" — a chaos test that passes
#: because its fault never triggered proves nothing).
_fired: Dict[str, int] = {}


def hook(point: str, **ctx) -> Optional[str]:
    """Failure-point call site.  No-op unless ``point`` is armed AND the
    arming's ``match`` accepts the keyword context.

    Returns ``"drop"`` / ``"duplicate"`` for those modes (the call site
    implements the semantics), ``None`` otherwise.  The disarmed fast
    path is one dict read with no lock — cheap enough for per-chunk,
    per-heartbeat and per-RPC sites.
    """
    if not _points:
        return None
    with _lock:
        armings = _points.get(point)
        if not armings:
            return None
        arming = None
        for a in armings:
            # An EXHAUSTED arming must not shadow later armings on the
            # same point: a spent count=1 verb-scoped drop would
            # otherwise silently neuter a partition armed afterwards.
            if a.remaining == 0:
                continue
            if _ctx_matches(a.match, ctx):
                arming = a
                break
        if arming is None:
            return None
        if arming.skip > 0:
            arming.skip -= 1
            return None
        if arming.remaining > 0:
            arming.remaining -= 1
        arming.fired += 1
        _fired[point] = _fired.get(point, 0) + 1
        mode, delay_s = arming.mode, arming.delay_s
    # Flight recorder: fault firings are exactly the "why did THAT
    # happen" events a post-hoc tail must contain.  Recorded before the
    # kill so the evidence lands even when the process dies here.
    try:
        from ray_tpu._private.debug import flight_recorder
        flight_recorder.record("fault.fired", point=point, mode=mode,
                               delay_s=delay_s, **ctx)
    except Exception:
        pass
    if mode == "delay":
        time.sleep(delay_s)
        return None
    if mode in ("drop", "duplicate"):
        return mode
    if mode == "kill":
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    raise FaultInjectedError(point)


def arm(point: str, mode: str = "error", count: int = 1, skip: int = 0,
        delay_s: float = 0.0, match: Optional[dict] = None) -> None:
    """Arm ``point``: the next ``count`` hits (after ``skip`` free
    passes) whose context matches ``match`` inject ``mode``.  Re-arming
    with the SAME match replaces that arming; a different match adds a
    second, independently-counted arming on the point."""
    if mode not in _MODES:
        raise ValueError(f"unknown fault mode {mode!r}")
    new = _Arming(mode, count, skip, delay_s, match)
    with _lock:
        armings = _points.setdefault(point, [])
        for i, a in enumerate(armings):
            if a.match == new.match:
                armings[i] = new
                return
        armings.append(new)


def disarm(point: Optional[str] = None,
           match: Optional[dict] = None) -> None:
    """Disarm one point (optionally only the arming with exactly
    ``match``), or every point when ``point`` is None (test teardown).
    Fired counts are kept."""
    with _lock:
        if point is None:
            _points.clear()
            return
        if match is None:
            _points.pop(point, None)
            return
        armings = _points.get(point)
        if armings:
            armings[:] = [a for a in armings if a.match != match]
            if not armings:
                _points.pop(point, None)


def fired(point: str) -> int:
    """Times ``point`` actually injected (cumulative, survives disarm)."""
    with _lock:
        return _fired.get(point, 0)


def reset() -> None:
    """Full reset: disarm everything and zero the fired counters."""
    with _lock:
        _points.clear()
        _fired.clear()


def load_from_env(env: Optional[str] = None) -> None:
    """Parse ``RAY_TPU_FAULT_POINTS`` — how spawned daemons (node_host,
    worker_main) inherit a test's arming across the process boundary."""
    raw = env if env is not None else os.environ.get(
        "RAY_TPU_FAULT_POINTS", "")
    if not raw:
        return
    for part in raw.split(","):
        try:
            fields = part.strip().split(":")
            if len(fields) < 2:
                continue
            point, mode = fields[0], fields[1]
            match = None
            if "@" in point:
                point, _, spec = point.partition("@")
                match = {}
                for kv in spec.split("&"):
                    k, _, v = kv.partition("=")
                    if k and v:
                        match[k] = v
            count = int(fields[2]) if len(fields) > 2 else 1
            delay_s = float(fields[3]) if len(fields) > 3 else 0.0
            arm(point, mode, count=count, delay_s=delay_s, match=match)
        except ValueError:
            continue


# ---------------------------------------------------------------------------
# Wire partitions: asymmetric drop-sets armed ACROSS processes.
# ---------------------------------------------------------------------------

def arm_over_wire(client, point: str, mode: str = "error", count: int = 1,
                  skip: int = 0, delay_s: float = 0.0,
                  match: Optional[dict] = None,
                  timeout: float = 10.0) -> None:
    """Arm a fault point in a REMOTE daemon over its ``arm_fault`` verb
    (exempt from the wire fault points, so this works mid-partition)."""
    client.call("arm_fault", {"point": point, "mode": mode, "count": count,
                              "skip": skip, "delay_s": delay_s,
                              "match": match}, timeout=timeout)


def disarm_over_wire(client, point: str, match: Optional[dict] = None,
                     timeout: float = 10.0) -> None:
    client.call("disarm_fault", {"point": point, "match": match},
                timeout=timeout)


class partition:
    """Asymmetric wire partition around one spawned daemon.

    Arms drop-mode wire faults IN the daemon's process over the
    fault-exempt ``arm_fault``/``disarm_fault`` verbs, so the partition
    can always be healed no matter which directions are cut:

    * ``outbound`` — the daemon's client-side ``rpc.send`` drops every
      request it originates (heartbeats, metrics reports, location
      rows, wedge reports never reach the head; peer pulls never reach
      peers), scoped by ``peer`` (default every peer);
    * ``inbound`` — the daemon's server-side ``rpc.recv`` drops every
      request arriving at it (lease pushes, resource broadcasts, chunk
      fetches die on its doorstep; their replies are implicitly never
      sent).

    One direction alone is the classic ASYMMETRIC partition: e.g.
    ``partition(client, inbound=False)`` makes the node look dead to
    the head (no heartbeats arrive) while the node itself still hears
    everything — the zombie-producing shape.  Context manager: arms on
    enter, heals on exit; or call :meth:`arm`/:meth:`heal` explicitly.
    """

    def __init__(self, target, outbound: bool = True, inbound: bool = True,
                 peer: str = "*"):
        """``target`` is an RpcClient to the daemon's server, or its
        (host, port) address — the helper then dials its OWN client, so
        healing still works after the head declared the node dead and
        closed the proxy's connection."""
        if hasattr(target, "call"):
            self._client = target
            self._own_client = False
        else:
            from ray_tpu.rpc import RpcClient
            self._client = RpcClient(tuple(target))
            self._own_client = True
        self._outbound = outbound
        self._inbound = inbound
        self._peer = peer
        self._armed = False

    def arm(self) -> "partition":
        if self._outbound:
            arm_over_wire(self._client, "rpc.send", "drop", count=-1,
                          match={"peer": self._peer})
        if self._inbound:
            arm_over_wire(self._client, "rpc.recv", "drop", count=-1,
                          match={"peer": self._peer} if self._peer != "*"
                          else None)
        self._armed = True
        return self

    def heal(self) -> None:
        if not self._armed:
            return
        if self._outbound:
            disarm_over_wire(self._client, "rpc.send",
                             match={"peer": self._peer})
        if self._inbound:
            disarm_over_wire(self._client, "rpc.recv",
                             match={"peer": self._peer}
                             if self._peer != "*" else None)
        self._armed = False

    def close(self) -> None:
        if self._own_client:
            self._client.close()

    def __enter__(self) -> "partition":
        return self.arm()

    def __exit__(self, *_exc) -> None:
        self.heal()
        self.close()


load_from_env()
