"""Deterministic fault injection for chaos testing.

Parity: the reference's chaos tooling is probabilistic (``test_chaos.py``
``NodeKillerActor`` kills a random node every N seconds) which makes
failures unreproducible under CI load.  Here failure POINTS are named
call sites compiled into the runtime — each ``hook(point)`` is a no-op
until a test arms it — and arming is count-based, not random, so a test
that says "fail the first two spill writes" fails exactly those two,
every run, on every machine.

Named points wired into the runtime (grep ``fault_injection.hook``):

========================  ====================================================
``spill.write``           before a spill batch is written to disk
``restore.read``          before a spilled object is read back
``transfer.chunk``        per received chunk of a streamed object transfer
``transfer.relay``        per relay read served from an IN-FLIGHT transfer's
                          assembled prefix (chunk relay, sender side)
``node.heartbeat``        before a raylet sends its GCS heartbeat
``worker.dispatch``       before a scheduled task is handed to local dispatch
``worker.lease_batch``    before a batched lease request enters scheduling
``loop.stall``            before an EventLoop executes a handler (delay mode
                          wedges the loop — the stall-watchdog drill)
``lock.hold``             after a diag lock is acquired (delay mode extends
                          the hold — attributable contention for the
                          profiling plane; only fires on witness/contention
                          wrapped locks)
========================  ====================================================

Modes:

* ``error`` — raise :class:`FaultInjectedError` at the hook;
* ``delay`` — sleep ``delay_s`` at the hook (slow-IO / slow-network);
* ``kill``  — ``SIGKILL`` the calling process (real process death; for
  node-host / worker OS processes).

Arming is in-process via :func:`arm` or cross-process via the
``RAY_TPU_FAULT_POINTS`` env var (parsed at import in every daemon):

    RAY_TPU_FAULT_POINTS="spill.write:error:2,transfer.chunk:delay:-1:0.05"

format per entry: ``point:mode[:count[:delay_s]]`` (count -1 = every
hit).  Malformed entries are skipped, never fatal: this parses at
import time in every daemon, and a typo in an env var must not take
the cluster down.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from ray_tpu import exceptions


class FaultInjectedError(exceptions.RayTpuError):
    """Raised by an armed ``error``-mode failure point."""

    def __init__(self, point: str):
        self.point = point
        super().__init__(f"injected fault at {point!r}")


class _Arming:
    __slots__ = ("mode", "remaining", "skip", "delay_s", "fired")

    def __init__(self, mode: str, count: int, skip: int, delay_s: float):
        self.mode = mode
        self.remaining = count     # -1 = unlimited
        self.skip = skip           # let the first N hits through
        self.delay_s = delay_s
        self.fired = 0


_lock = threading.Lock()
_points: Dict[str, _Arming] = {}
#: Total hits per point since arming began (kept after disarm so tests
#: can assert "the fault actually fired" — a chaos test that passes
#: because its fault never triggered proves nothing).
_fired: Dict[str, int] = {}


def hook(point: str) -> None:
    """Failure-point call site.  No-op unless ``point`` is armed.

    The disarmed fast path is one dict read with no lock — cheap enough
    for per-chunk and per-heartbeat sites.
    """
    if not _points:
        return
    with _lock:
        arming = _points.get(point)
        if arming is None:
            return
        if arming.skip > 0:
            arming.skip -= 1
            return
        if arming.remaining == 0:
            return
        if arming.remaining > 0:
            arming.remaining -= 1
        arming.fired += 1
        _fired[point] = _fired.get(point, 0) + 1
        mode, delay_s = arming.mode, arming.delay_s
    # Flight recorder: fault firings are exactly the "why did THAT
    # happen" events a post-hoc tail must contain.  Recorded before the
    # kill so the evidence lands even when the process dies here.
    try:
        from ray_tpu._private.debug import flight_recorder
        flight_recorder.record("fault.fired", point=point, mode=mode,
                               delay_s=delay_s)
    except Exception:
        pass
    if mode == "delay":
        time.sleep(delay_s)
        return
    if mode == "kill":
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    raise FaultInjectedError(point)


def arm(point: str, mode: str = "error", count: int = 1, skip: int = 0,
        delay_s: float = 0.0) -> None:
    """Arm ``point``: the next ``count`` hits (after ``skip`` free
    passes) inject ``mode``.  Re-arming replaces the previous arming."""
    if mode not in ("error", "delay", "kill"):
        raise ValueError(f"unknown fault mode {mode!r}")
    with _lock:
        _points[point] = _Arming(mode, count, skip, delay_s)


def disarm(point: Optional[str] = None) -> None:
    """Disarm one point, or every point when ``point`` is None (test
    teardown).  Fired counts are kept."""
    with _lock:
        if point is None:
            _points.clear()
        else:
            _points.pop(point, None)


def fired(point: str) -> int:
    """Times ``point`` actually injected (cumulative, survives disarm)."""
    with _lock:
        return _fired.get(point, 0)


def reset() -> None:
    """Full reset: disarm everything and zero the fired counters."""
    with _lock:
        _points.clear()
        _fired.clear()


def load_from_env(env: Optional[str] = None) -> None:
    """Parse ``RAY_TPU_FAULT_POINTS`` — how spawned daemons (node_host,
    worker_main) inherit a test's arming across the process boundary."""
    raw = env if env is not None else os.environ.get(
        "RAY_TPU_FAULT_POINTS", "")
    if not raw:
        return
    for part in raw.split(","):
        try:
            fields = part.strip().split(":")
            if len(fields) < 2:
                continue
            point, mode = fields[0], fields[1]
            count = int(fields[2]) if len(fields) > 2 else 1
            delay_s = float(fields[3]) if len(fields) > 3 else 0.0
            arm(point, mode, count=count, delay_s=delay_s)
        except ValueError:
            continue


load_from_env()
