"""Worker-host process runtime: a full Raylet joined to a remote head
over the framed-RPC wire.

Parity: reference raylet process (``src/ray/raylet/main.cc`` — plasma +
NodeManager in one daemon) registering with the GCS over gRPC
(``NodeInfoGcsService``), heartbeating
(``gcs_heartbeat_manager.h:31-60``), serving the lease protocol
(``node_manager.proto:300-357``) and object pulls
(``object_manager.proto:61``) to remote peers.

Design: the REAL in-process ``Raylet`` runs here unchanged — scheduler
queues, worker pool, object store, dependency manager.  What differs is
the *cluster adapter* handed to it: control-plane surfaces forward over
one RpcClient to the head process, while OBJECT pulls dial peer
node-hosts directly (``PeerPool``) using addresses the head's directory
hands out — node-to-node chunked transfer exactly like the reference's
ObjectManagerService, with the head relay kept only as a fallback.  The
head mirrors this node as a ``RemoteNodeProxy`` (head_service.py) that
duck-types Raylet for the GCS and the driver-side submitters, so
neither side's runtime code knows the wire exists.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ray_tpu import exceptions
from ray_tpu._private import fault_injection
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.object_store import MemoryStore
from ray_tpu._private.serialization import (
    SerializedObject, loads_function, serialize)
from ray_tpu.rpc import RpcClient, RpcConnectionError, RpcServer
from ray_tpu._private.debug import diag_lock


class _RemoteHeartbeats:
    def __init__(self, host: "NodeHost"):
        self._host = host

    def heartbeat(self, node_id: NodeID):
        self._host.client.call_async(
            "heartbeat", self._host.stamp({"node_id": node_id.binary()}),
            self._host.fence_watch())
        # The emitter buffer only flushes from emit(): piggyback on the
        # raylet's heartbeat loop so the tail of events after the LAST
        # emit on this node (e.g. the final task's RUNNING) still
        # reaches the head once the node quiesces — the query layer's
        # read-your-writes flush can only reach the head's own buffer.
        buf = getattr(self._host.adapter.gcs, "task_events", None)
        if buf is not None and buf.num_buffered():
            buf.flush()
        # Observability plane rides the same channel (all async — the
        # heartbeat thread must never block on a slow head): clock-sync
        # probes, metrics delta snapshots, buffered tracing spans.
        self._host.clock_sync.maybe_probe()
        self._host.maybe_ship_observability()


class _ClockSync:
    """Per-node clock-offset estimation to the head (RTT-anchored on
    the heartbeat channel): ``offset_s`` added to a local wall-clock
    timestamp yields head-clock time.  NTP-style midpoint estimate,
    keeping the tightest (lowest-RTT) sample — the estimate's error is
    bounded by rtt/2, so the best sample wins; the bound decays slowly
    so genuine drift is re-tracked.  All probes are async: a wedged
    head degrades the estimate, never the heartbeat loop."""

    _PROBE_INTERVAL_S = 5.0

    def __init__(self, client: RpcClient):
        self._client = client
        self._last_probe = 0.0
        self._best_rtt = float("inf")
        self._inflight = False
        self.offset_s = 0.0
        self.samples = 0

    def maybe_probe(self, now: Optional[float] = None):
        import time
        now = time.monotonic() if now is None else now
        # First probe fires immediately; then one per interval.
        if self._inflight or (
                self.samples and
                now - self._last_probe < self._PROBE_INTERVAL_S):
            return
        self._last_probe = now
        self._inflight = True
        t0_wall = time.time()
        t0_mono = time.monotonic()

        def on_reply(result, err):
            self._inflight = False
            if err is not None or result is None:
                return
            rtt = time.monotonic() - t0_mono
            t1_wall = time.time()
            # Loosen the accept bound slowly so drift re-tracks even if
            # the network never again matches the historic best RTT.
            self._best_rtt = min(self._best_rtt * 1.25 + 1e-4, 10.0)
            if rtt <= self._best_rtt:
                self._best_rtt = rtt
                self.offset_s = float(result) - (t0_wall + t1_wall) / 2.0
                self.samples += 1

        try:
            self._client.call_async("clock_probe", None, on_reply)
        except Exception:
            self._inflight = False


class _TimelineShipper:
    """Beat-budgeted timeline span shipper (ROADMAP item 1: heartbeat-
    channel congestion is a 64-node scale blocker — make observability's
    share of the channel measurable AND bounded).

    Each ship window grants ``timeline_ship_budget_bytes`` of budget;
    unused budget carries over (capped at ``_CARRYOVER_WINDOWS``
    windows) so a quiet node can absorb a later burst without ever
    exceeding the long-run byte rate.  Spans past the budget stay in a
    bounded pending queue for the next beat; queue overflow drops the
    OLDEST spans and counts them into the batch's drop counter (loss
    explicit, task-event-buffer semantics).  Every shipped batch's
    payload bytes are recorded as ``ray_tpu_heartbeat_payload_bytes``
    (kind="timeline")."""

    _CARRYOVER_WINDOWS = 4
    _PENDING_CAP = 50_000

    def __init__(self, publish, source: str, node_hex: str, offset_fn):
        from collections import deque
        self._publish = publish
        self._source = source
        self._node_hex = node_hex
        self._offset_fn = offset_fn
        self._pending = deque()
        self._budget = 0.0
        self.dropped = 0          # shipper-side queue overflow, cumulative
        self.shipped_bytes = 0
        self.shipped_batches = 0
        self.windows_shed = 0     # windows skipped by the channel budget

    def _drain_into_pending(self):
        from ray_tpu.util import tracing
        if not tracing.num_buffered():
            return
        events = tracing.drain()
        self._pending.extend(events)
        overflow = len(self._pending) - self._PENDING_CAP
        for _ in range(max(0, overflow)):
            self._pending.popleft()
            self.dropped += 1

    def ship(self, budget_cap: Optional[int] = None) -> int:
        """One beat: refresh the budget, ship the prefix of pending
        spans that fits, return the bytes shipped.  ``budget_cap``
        (heartbeat-channel congestion control) further clamps THIS
        window's grant — the shared per-beat channel budget left after
        higher-priority payloads (liveness is never charged, metrics
        deltas go first).  A zero cap skips the window entirely: the
        spans stay pending (bounded queue, drops counted), which is
        shedding, not loss."""
        import pickle

        from ray_tpu._private.config import get_config
        from ray_tpu._private.metrics_agent import record_internal
        from ray_tpu.util import tracing
        per_beat = max(1, int(get_config().timeline_ship_budget_bytes))
        if budget_cap is not None:
            if budget_cap <= 0:
                self.windows_shed += 1
                return 0
            per_beat = min(per_beat, int(budget_cap))
        self._budget = min(self._budget + per_beat,
                           per_beat * self._CARRYOVER_WINDOWS)
        self._drain_into_pending()
        if not self._pending:
            return 0
        if self._budget <= 0:
            # Repaying debt from an oversized single-span ship: skip
            # this window so the LONG-RUN byte rate stays bounded (the
            # progress guarantee below would otherwise overshoot the
            # budget forever on a stream of oversized spans).
            return 0
        batch, size = [], 0
        while self._pending:
            ev = self._pending[0]
            try:
                ev_size = len(pickle.dumps(ev, protocol=4)) + 16
            except Exception:
                self._pending.popleft()     # unpicklable span: drop it
                self.dropped += 1
                continue
            # Progress guarantee: a single span larger than the whole
            # budget still ships (alone) rather than wedging the queue.
            if batch and size + ev_size > self._budget:
                break
            self._pending.popleft()
            batch.append(ev)
            size += ev_size
        if not batch:
            return 0
        from ray_tpu.gcs.pubsub import TIMELINE_CHANNEL
        try:
            self._publish(
                TIMELINE_CHANNEL, b"",
                {"source": self._source,
                 "node_id": self._node_hex,
                 "clock_offset_us": self._offset_fn() * 1e6,
                 "dropped": tracing.dropped_count() + self.dropped,
                 "events": batch})
        except Exception:
            # Failed publish: the spans go BACK to the queue head (the
            # budget was not charged, the next beat retries) — popping
            # them before a flaky send would be silent loss, the exact
            # failure mode this class's accounting exists to prevent.
            self._pending.extendleft(reversed(batch))
            raise
        # No zero-clamp: an oversized span drives the budget negative
        # (debt), and later windows pay it down before shipping again.
        self._budget -= size
        self.shipped_bytes += size
        self.shipped_batches += 1
        record_internal("ray_tpu.heartbeat.payload_bytes", size,
                        mtype="counter", kind="timeline",
                        node=self._node_hex)
        record_internal("ray_tpu.timeline.ship_backlog_events",
                        len(self._pending), node=self._node_hex)
        return size


class _RemoteActorManager:
    def __init__(self, host: "NodeHost"):
        self._host = host

    def on_actor_worker_died(self, actor_id, reason: str):
        self._host.client.call_async(
            "actor_worker_died",
            self._host.stamp({"actor_id": actor_id, "reason": reason}),
            self._host.fence_watch())


class _RemotePublisher:
    """Pubsub publishes forwarded to the head's GCS publisher with
    long-poll-style batching: at most one RPC in flight, everything
    behind it rides the next flush (the worker-log stream spams this —
    reference publisher.h O(#subscribers) property, mirrored on the
    publish side)."""

    def __init__(self, host: "NodeHost"):
        from ray_tpu.gcs.wire_pubsub import BatchingPublisher
        self._batcher = BatchingPublisher(host.client)

    def publish(self, channel: str, key: bytes, message):
        self._batcher.publish(channel, key, message)


class _RemoteGcs:
    """The slice of the GCS surface a raylet touches, over the wire."""

    def __init__(self, host: "NodeHost"):
        import uuid

        from ray_tpu.gcs.task_events import TaskEventBuffer
        self._host = host
        self.heartbeat_manager = _RemoteHeartbeats(host)
        self.actor_manager = _RemoteActorManager(host)
        self.kv = _RemoteKV(host)
        self.publisher = _RemotePublisher(host)
        # Task-event emissions from this node (raylet SCHEDULED, worker
        # RUNNING, ...) batch over the wire publisher; the head's
        # WirePubsubService re-publishes into the GCS plane where the
        # TaskEventManager subscribes — remote nodes report the same
        # lifecycle detail as the head's own raylet.  buffer_id must be
        # unique per incarnation (pids collide across machines and
        # restarts): the manager keys per-source drop counters on it.
        # Timestamps are normalized to the head clock at emit so the
        # manager's cross-buffer stage durations compare like clocks.
        self.task_events = TaskEventBuffer(
            self.publisher, buffer_id=f"node-{uuid.uuid4().hex[:12]}",
            ts_offset=lambda: host.clock_sync.offset_s)

    def raylet(self, node_id: NodeID):
        """Peer lookup for object pulls: every peer is reachable through
        the head (hub-and-spoke), so hand back one fetch proxy."""
        return _PeerFetchProxy(self._host, node_id)

    def unregister_raylet(self, node_id: NodeID):
        try:
            self._host.client.call(
                "unregister_node", {"node_id": node_id.binary()},
                timeout=5.0)
        except Exception:
            pass


class _RemoteKV:
    def __init__(self, host: "NodeHost"):
        self._host = host

    def get(self, key: bytes) -> Optional[bytes]:
        return self._host.client.call("kv_get", key, timeout=30.0)


class _PeerStoreReader:
    """Reads a peer node's store.  Pulls are peer-to-peer: dial the peer
    directly (address from the head's directory, ``PeerPool``) and pull
    chunked from its chunk server; the head link is only the fallback
    for peers we cannot resolve or dial (ObjectManagerService pull
    parity, ``object_manager.proto:61``)."""

    def __init__(self, host: "NodeHost", node_id: NodeID):
        self._host = host
        self._node_id = node_id

    def get_serialized(self, object_id: ObjectID
                       ) -> Optional[SerializedObject]:
        from ray_tpu.rpc.chunked import fetch_chunked
        peer = self._host.peers.client_for(self._node_id)
        if peer is not None:
            try:
                blob = fetch_chunked(peer, object_id.binary(),
                                     timeout=300.0)
                if blob is not None:
                    return SerializedObject.from_bytes(blob)
            except Exception:
                self._host.peers.drop(self._node_id)
        blob = fetch_chunked(self._host.client, object_id.binary(),
                             timeout=300.0)
        return None if blob is None else SerializedObject.from_bytes(blob)

    def fetch_into(self, object_id: ObjectID, local_store,
                   pipeline: int = 8, on_chunk=None,
                   timeout: float = 300.0,
                   busy_patience_s: Optional[float] = None
                   ) -> Optional[int]:
        """Streamed pull: assemble the windowed chunk pipeline DIRECTLY
        into a reserved block of ``local_store`` (no intermediate
        ``bytearray`` — the zero-copy receive half of the data plane).
        Tries the direct peer link first, the head link as fallback."""
        from ray_tpu import exceptions as exc
        from ray_tpu._private.object_manager import fetch_object_into
        peer = self._host.peers.client_for(self._node_id)
        for client in ([peer] if peer is not None else []) + \
                [self._host.client]:
            try:
                nbytes = fetch_object_into(
                    client, object_id, local_store, pipeline=pipeline,
                    on_chunk=on_chunk, timeout=timeout,
                    busy_patience_s=busy_patience_s)
            except exc.ObjectStoreFullError as err:
                # LOCAL store cannot take the object: the peer is not
                # at fault (don't tear its link down) and the head leg
                # would fail identically — surface the failure.  The
                # infeasible variant (object larger than the whole
                # store) propagates so the executor fails the task with
                # the actionable raise-object_store_memory message
                # instead of looping its 60s arg-fetch deadline.
                if getattr(err, "infeasible", False):
                    raise
                return None
            except Exception:
                nbytes = None
                if client is peer:
                    self._host.peers.drop(self._node_id)
            if nbytes is not None:
                return nbytes
        return None

    def get(self, object_id: ObjectID):
        return None

    def delete(self, object_id: ObjectID):
        pass


class PeerPool:
    """Cache of direct connections to peer node-hosts, keyed by node id.

    Addresses come from directory answers (``get_locations`` /
    ``wait_object`` entries carry host:port) or an explicit head lookup
    (``get_node_address``).  One RpcClient per peer, created lazily,
    dropped on transfer failure so a restarted peer re-dials cleanly
    (reference: ObjectManager's connection pool per remote node)."""

    def __init__(self, host: "NodeHost"):
        self._host = host
        self._lock = diag_lock("PeerPool._lock")
        self._addrs: Dict[NodeID, tuple] = {}
        self._clients: Dict[NodeID, RpcClient] = {}

    def note_address(self, node_id: NodeID, host_addr, port):
        if host_addr is None or port is None:
            return
        with self._lock:
            self._addrs[node_id] = (host_addr, int(port))

    def client_for(self, node_id: NodeID) -> Optional[RpcClient]:
        """Direct client to a peer, or None when the target is the head
        / unknown (caller uses the head link)."""
        with self._lock:
            client = self._clients.get(node_id)
            if client is not None:
                return client
            addr = self._addrs.get(node_id)
        if addr is None:
            try:
                reply = self._host.client.call(
                    "get_node_address", {"node_id": node_id.binary()},
                    timeout=10.0)
            except Exception:
                return None
            if reply is None:
                return None
            addr = (reply[0], int(reply[1]))
            with self._lock:
                self._addrs[node_id] = addr
        if addr == self._host.server.address:
            return None     # self-dial: bytes are local, not a pull
        try:
            client = RpcClient(addr)
        except Exception:
            return None
        with self._lock:
            existing = self._clients.get(node_id)
            if existing is not None:
                close_me, client = client, existing
            else:
                self._clients[node_id] = client
                close_me = None
        if close_me is not None:
            close_me.close()
        return client

    def drop(self, node_id: NodeID):
        with self._lock:
            self._addrs.pop(node_id, None)
            client = self._clients.pop(node_id, None)
        if client is not None:
            client.close()

    def close_all(self):
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            self._addrs.clear()
        for c in clients:
            c.close()


class _PeerFetchProxy:
    def __init__(self, host: "NodeHost", node_id: NodeID):
        self.node_id = node_id
        self.object_store = _PeerStoreReader(host, node_id)


class _RemoteDirectory:
    """Object location directory backed by the head's authoritative one.

    ``get_locations`` includes the head itself when the owner's memory
    store holds the (small, never directory-registered) value — the
    ``fetch_object`` handler serves both cases."""

    def __init__(self, host: "NodeHost"):
        self._host = host

    def add_location(self, object_id: ObjectID, node_id: NodeID,
                     size: Optional[int] = None):
        self._host.client.call_async(
            "add_location",
            self._host.stamp(
                {"object_id": object_id.binary(),
                 "node_id": node_id.binary(),
                 "size": int(size or 0)}),
            self._host.fence_watch())

    # NOTE no size_hint here, deliberately: spoke-side schedulers have
    # no local size table (the head's directory, where the batched
    # solve runs, carries the hints), and ClusterTaskManager's
    # hasattr(directory, "size_hint") gate must short-circuit so spoke
    # ticks don't walk every queued spec's args for guaranteed-zero
    # locality data.

    def remove_location(self, object_id, node_id):
        # Must be real, not a no-op: the vanished-entry heal removes
        # this node's stale row so the head stops redirecting pulls to
        # a copy-less node (the row never "ages out" for a live node).
        self._host.client.call_async(
            "remove_location",
            self._host.stamp(
                {"object_id": object_id.binary(),
                 "node_id": node_id.binary()}),
            self._host.fence_watch())

    def add_partial_location(self, object_id: ObjectID,
                             node_id: NodeID) -> int:
        """Register this node's in-flight pull as a relayable PARTIAL
        row at the head's directory.  Synchronous: the returned seq is
        what makes relay chains cycle-free (we may only relay FROM
        lower-seq rows), so the pull cannot proceed without it."""
        seq = self._host.client.call(
            "add_partial_location",
            self._host.stamp(
                {"object_id": object_id.binary(),
                 "node_id": node_id.binary()}),
            timeout=10.0)
        if seq is None:
            raise RuntimeError("head rejected partial registration")
        return int(seq)

    def remove_partial_location(self, object_id: ObjectID,
                                node_id: NodeID):
        # Stamped like every other head-bound directory write: an
        # un-stamped removal from a STALE incarnation could erase the
        # live incarnation's in-flight PARTIAL row (graftcheck R10
        # caught this as the one directory verb missing the fence).
        self._host.client.call_async(
            "remove_partial_location",
            self._host.stamp(
                {"object_id": object_id.binary(),
                 "node_id": node_id.binary()}),
            self._host.fence_watch())

    def remove_object(self, object_id):
        pass

    def _entries(self, object_id: ObjectID):
        try:
            locs = self._host.client.call(
                "get_locations", {"object_id": object_id.binary()},
                timeout=10.0)
        except Exception:
            return []
        for entry in locs:
            self._host.peers.note_address(
                NodeID(entry["node_id"]), entry.get("host"),
                entry.get("port"))
        return locs

    def get_locations(self, object_id: ObjectID):
        return {NodeID(e["node_id"]) for e in self._entries(object_id)
                if not e.get("partial")}

    def get_candidates(self, object_id: ObjectID):
        """Full + partial rows with the head's load hints (the spoke
        has no cross-node ledger visibility — the head's resource polls
        carry each node's outbound-transfer load)."""
        return [{"node_id": NodeID(e["node_id"]),
                 "partial": bool(e.get("partial")),
                 "seq": int(e.get("seq") or 0),
                 "size": int(e.get("size") or 0),
                 "load": e.get("load")}
                for e in self._entries(object_id)]

    def subscribe_location(self, object_id: ObjectID, cb: Callable):
        """One async ``wait_object`` call: the head blocks event-driven
        (directory subscription + owner memory-store future) and replies
        with a location, or None on timeout — which flows back through
        the pull path as a failed pull instead of a silent hang."""

        def on_done(result, err):
            if self._host.stopped:
                return
            if err is not None or result is None:
                cb(None)     # timed out / head gone -> failed pull
            else:
                node_id = NodeID(result["node_id"])
                self._host.peers.note_address(
                    node_id, result.get("host"), result.get("port"))
                cb(node_id)

        self._host.client.call_async(
            "wait_object",
            {"object_id": object_id.binary(), "timeout": 30.0}, on_done)

    def on_node_death(self, node_id):
        return []


class _RemoteCoreWorker:
    """The executor-facing core-worker surface on a worker-host node.

    Executing workers need: arg materialization (get_for_executor),
    return storage with owner semantics (put_return_value), the function
    store, and a memory-store handle for the object manager's inline
    checks.  Ownership itself stays with the driver on the head — this
    shim ships small returns to the owner and registers big ones in the
    directory, exactly what the reference executor does via its plasma +
    owner RPCs."""

    is_driver = False

    def __init__(self, host: "NodeHost"):
        self._host = host
        self.memory_store = MemoryStore()   # local scratch; misses -> pull
        self.function_manager = _RemoteFunctionManager(host)
        self.reference_counter = _AlwaysReferenced()
        self.task_manager = _NeverPending()

    def get_for_executor(self, object_id: ObjectID, node):
        """Executor-side arg wait (GetAndPinArgsForExecutor parity).

        A granted lease may be used for ANY queued task of its
        scheduling class (direct_task_transport.cc:157 worker reuse), so
        an arg can legitimately not exist yet when the task arrives —
        the executor must block until the owner produces it.  Loop:
        local store -> owner fetch (errors propagate) -> event-driven
        ``wait_object`` on the head, bounded by a deadline.

        A FAILED pull (the directory redirected us to a peer that died
        with the bytes, or a chunk session tore mid-transfer) is NOT a
        lost object: the owner reconstructs lost objects from lineage
        once the node is declared dead, so the executor loops — re-ask,
        short backoff — and only the deadline turns persistent failure
        into ObjectLostError.  Raising on the first failed pull would
        fail the whole task over a loss the owner was about to repair.
        """
        import pickle
        import time

        from ray_tpu._private.object_store import (ObjectVanishedError,
                                                   entry_value)
        from ray_tpu._private.serialization import deserialize

        deadline = time.monotonic() + 60.0
        last_failure = None
        while True:
            entry = node.object_store.get(object_id)
            if entry is not None:
                try:
                    return entry_value(entry)
                except ObjectVanishedError:
                    # Concurrent free: heal the poisoned entry AND this
                    # node's stale directory row at the head (or every
                    # pull keeps getting redirected here), then fall
                    # through to re-fetch from a real location.
                    if node.object_store.drop_vanished(object_id):
                        self._host.adapter.object_directory \
                            .remove_location(object_id, node.node_id)
            result = self._host.client.call(
                "fetch_value", {"object_id": object_id.binary()},
                timeout=60.0)
            if result is not None:
                kind, blob = result
                if kind == "error":
                    raise pickle.loads(blob)
                if kind == "remote":
                    # Owner redirect: pull the bytes peer-to-peer.
                    peer_id = NodeID(blob["node_id"])
                    self._host.peers.note_address(
                        peer_id, blob.get("host"), blob.get("port"))
                    reader = _PeerStoreReader(self._host, peer_id)
                    try:
                        serialized = reader.get_serialized(object_id)
                    except Exception:
                        serialized = None
                    if serialized is not None:
                        return deserialize(serialized)
                    last_failure = "peer arg fetch failed"
                elif kind == "chunked":
                    from ray_tpu.rpc.chunked import (
                        fetch_chunked, fetch_session)
                    try:
                        if blob is not None:  # pre-opened session meta
                            blob = fetch_session(self._host.client, blob,
                                                 timeout=300.0)
                        else:                 # admission-full: retry path
                            blob = fetch_chunked(self._host.client,
                                                 object_id.binary(),
                                                 timeout=300.0)
                    except Exception:
                        blob = None
                    if blob is not None:
                        return deserialize(
                            SerializedObject.from_bytes(blob))
                    last_failure = "chunked arg fetch failed"
                else:
                    return deserialize(SerializedObject.from_bytes(blob))
                if time.monotonic() >= deadline:
                    raise exceptions.ObjectLostError(
                        object_id, last_failure)
                # Re-ask after a beat: the stale location must age out
                # (heartbeat timeout) before the directory stops
                # redirecting us to the dead peer.
                time.sleep(0.2)
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise exceptions.ObjectLostError(
                    object_id, "arg fetch timed out")
            self._host.client.call(
                "wait_object",
                {"object_id": object_id.binary(),
                 "timeout": min(remaining, 10.0)},
                timeout=remaining + 10.0)

    def put_return_value(self, object_id: ObjectID, value, node) -> int:
        serialized = serialize(value)
        self.put_serialized_return(object_id, serialized, node)
        return serialized.total_bytes

    def put_serialized_return(self, object_id: ObjectID, serialized,
                              node):
        """Owner lives on the head: ship small returns to its memory
        store (inline reply), register big ones in the directory.  Both
        paths are incarnation-stamped: a fenced (zombie) worker's
        return must not land in the owner's store or the directory."""
        from ray_tpu._private.config import get_config
        if serialized.total_bytes <= get_config().max_direct_call_object_size:
            result = self._host.client.call(
                "put_inline",
                self._host.stamp(
                    {"object_id": object_id.binary(),
                     "blob": serialized.to_bytes()}),
                timeout=60.0)
        else:
            node.object_store.put(object_id, serialized)
            result = self._host.client.call(
                "add_location",
                self._host.stamp(
                    {"object_id": object_id.binary(),
                     "node_id": node.node_id.binary(),
                     "size": int(serialized.total_bytes)}),
                timeout=30.0)
        if isinstance(result, dict) and result.get("fenced"):
            self._host.on_fenced(result)
            raise exceptions.WorkerCrashedError(
                "return rejected: node incarnation fenced")

    def recover_object(self, object_id) -> bool:
        return False

    def record_task_metric(self, spec, elapsed: float):
        pass

    def on_node_death(self, node_id, lost):
        pass


class _RemoteFunctionManager:
    def __init__(self, host: "NodeHost"):
        self._host = host
        self._cache: Dict = {}

    def load(self, function_id):
        from ray_tpu._private.function_manager import _KV_PREFIX
        fn = self._cache.get(function_id)
        if fn is None:
            blob = self._host.client.call(
                "kv_get", _KV_PREFIX + function_id.binary(), timeout=30.0)
            if blob is None:
                raise KeyError(f"function {function_id} not in GCS KV")
            fn = loads_function(blob)
            self._cache[function_id] = fn
        return fn


class _AlwaysReferenced:
    def has_reference(self, _oid) -> bool:
        return True


class _NeverPending:
    def is_pending(self, _task_id) -> bool:
        return False


class _RemoteClusterAdapter:
    """What the local Raylet sees as its 'cluster'."""

    def __init__(self, host: "NodeHost"):
        self._host = host
        self.gcs = _RemoteGcs(host)
        self.object_directory = _RemoteDirectory(host)
        self.core_worker = None          # set to the shim after Raylet init


class NodeHost:
    """One worker-host process: local Raylet + RPC server + head link."""

    def __init__(self, head_address, resources: Dict[str, float],
                 node_name: str = "", reg_token: str = ""):
        from ray_tpu._private.raylet import Raylet
        self.stopped = False
        self.client = RpcClient(tuple(head_address))
        self.peers = PeerPool(self)
        #: Registration incarnation minted by the head; every head-bound
        #: message is stamped with it, and a ``{"fenced": True}`` reply
        #: (this incarnation was declared dead) triggers drain +
        #: re-register as a fresh incarnation.
        self.incarnation: Optional[int] = None
        self._fence_lock = diag_lock("NodeHost._fence_lock")
        self._refencing = False
        # Observability plane (before the adapter: the task-event
        # buffer's ts normalization closes over clock_sync).
        from ray_tpu._private.metrics_agent import MetricsDeltaShipper
        self.clock_sync = _ClockSync(self.client)
        self._metrics_shipper = MetricsDeltaShipper()
        self._last_metrics_ship = 0.0
        self._last_timeline_ship = 0.0
        self._timeline_shipper: Optional[_TimelineShipper] = None
        #: Metrics deltas shed by the heartbeat-channel byte budget
        #: (deferred + force-fulled, not lost) — the congestion
        #: control's own observability, readable over the wire via
        #: ``observability_stats``.
        self.metrics_sheds = 0
        self.adapter = _RemoteClusterAdapter(self)
        store_bytes = resources.get("object_store_memory")
        self.raylet = Raylet(
            self.adapter, resources, node_name=node_name,
            object_store_memory=int(store_bytes) if store_bytes else None)
        self.core_shim = _RemoteCoreWorker(self)
        self.raylet.core_worker = self.core_shim
        self.adapter.core_worker = self.core_shim
        self._workers: Dict[bytes, object] = {}   # lease token -> Worker
        self._grant_times: Dict[bytes, float] = {}
        self._workers_lock = diag_lock("NodeHost._workers_lock")

        self.server = RpcServer(
            name=f"nodehost-{self.raylet.node_id.hex()[:6]}")
        s = self.server
        s.register_async("request_worker_lease", self._handle_lease)
        s.register_async("request_worker_lease_batch",
                         self._handle_lease_batch)
        s.register_async("push_task", self._handle_push)
        s.register_async("assign_actor", self._handle_assign_actor)
        s.register_async("push_actor_task", self._handle_push_actor_task)
        s.register("return_worker", self._handle_return_worker)
        s.register("reconcile_leases", self._handle_reconcile_leases)
        s.register("update_resource_usage", self._handle_update_usage)
        s.register("get_resource_report",
                   lambda _p: self.raylet.get_resource_report())
        s.register("fetch_object", self._handle_fetch_object)
        s.register("delete_object", self._handle_delete_object)
        s.register("prepare_bundle", self._handle_prepare_bundle)
        s.register("commit_bundle", self._handle_commit_bundle)
        s.register("cancel_bundle", self._handle_cancel_bundle)
        s.register("ping", lambda _p: "pong")
        # Debug surface: how often a named fault point fired IN THIS
        # PROCESS — chaos tests armed via RAY_TPU_FAULT_POINTS prove
        # their fault actually triggered across the process boundary
        # (a chaos test whose fault never fired proves nothing).
        s.register("fault_fired",
                   lambda p: fault_injection.fired(p["point"]))
        # Heartbeat-channel congestion-control counters: how much
        # telemetry this node shed/shipped — the envelope's degradation
        # proof reads this per node instead of hoping the (possibly
        # shed) metrics plane delivered it.
        s.register("observability_stats", self._handle_observability_stats)
        # Deterministic wire arming (chaos tests that need a fault
        # AFTER startup, where env-var count-skipping is unpredictable
        # — e.g. one loop.stall wedge once the node is registered, or a
        # partition armed mid-workload).  Both verbs are EXEMPT from
        # the rpc.send/rpc.recv fault points (rpc.verbs CONTROL_VERBS)
        # so an armed partition can always be healed through them.
        s.register("arm_fault", self._handle_arm_fault)
        s.register("disarm_fault", self._handle_disarm_fault)
        # Introspection plane: this OS process's debug report (loops,
        # wedges, lock contention, flight-recorder tail, stacks) for
        # the head's cluster-wide `ray-tpu doctor` fan-out.
        from ray_tpu._private.debug.report import handle_debug_dump
        s.register("debug_dump", handle_debug_dump)
        # Wedge reports ship to the head as they fire, so the head
        # tracks INTERNAL loop liveness, not just node heartbeats (a
        # node with a wedged raylet loop still heartbeats — that is
        # precisely the failure shape heartbeats cannot see).
        from ray_tpu._private.debug import watchdog as watchdog_mod
        self._wedge_listener = self._make_wedge_listener()
        watchdog_mod.add_listener(self._wedge_listener)
        s.register("stop", self._handle_stop)
        from ray_tpu._private.object_store import (partial_chunk_source,
                                                   segment_chunk_source)
        from ray_tpu.rpc.chunked import serve_chunks
        self.chunk_server = serve_chunks(
            s, lambda oid_bin: self._handle_fetch_object(
                {"object_id": oid_bin}),
            get_source=segment_chunk_source(self.raylet.object_store),
            # Relay: downstream peers stream the assembled prefix of a
            # transfer still landing here; outbound sessions are
            # charged to the store's admission ledger.
            get_partial=partial_chunk_source(self.raylet.object_store),
            ledger=self.raylet.object_store.transfer_ledger)
        self._stop_event = threading.Event()

        # Join the cluster (NodeInfoGcsService RegisterNode parity).
        # The reply carries the incarnation the head minted for this
        # registration — the fencing identity of everything we send.
        self._register(reg_token)

    # ---- incarnation fencing -------------------------------------------
    def _register(self, reg_token: str = ""):
        """(Re-)register with the head; one payload builder for both
        the initial join and the post-fence rebirth so their fields can
        never drift apart.  The head's admission gate
        (``head_registration_concurrency``) may answer ``{"busy":
        True, "retry_after_ms"}`` during a registration storm: honor
        it with jittered backoff (deterministic per node id, so a
        64-host storm fans out instead of re-colliding) until a
        bounded deadline."""
        import time
        payload = {
            "node_id": self.raylet.node_id.binary(),
            "node_name": self.raylet.node_name,
            "resources": self.raylet.local_resources.to_float_dict("total"),
            "labels": dict(self.raylet.local_resources.labels),
            "host": self.server.address[0],
            "port": self.server.address[1],
            "reg_token": reg_token,
        }
        # Per-node deterministic jitter factor in [1.0, 1.5).
        jitter = 1.0 + (self.raylet.node_id.binary()[0] % 128) / 256.0
        # Short per-call timeout + long overall deadline: one congested
        # call burns ~timeout × client-retries seconds, so a 30s call
        # timeout leaves a 120s deadline room for barely one retry
        # round.  10s × 3 attempts = 30s/round -> ~10 rounds in 300s,
        # which rides out a 64-interpreter boot storm on a small box.
        deadline = time.monotonic() + 300.0
        conn_backoff_s = 0.25
        while True:
            try:
                reply = self.client.call("register_node", dict(payload),
                                         timeout=10.0)
            except RpcConnectionError:
                # A 64-host boot storm can starve the head (or this
                # process) past the client's bounded retries before the
                # admission gate even answers — that is the storm the
                # gate exists for, so keep trying until the same
                # deadline instead of dying on the first congested
                # window.  register_node re-sends are safe: the head
                # mints a fresh incarnation per registration and a
                # node's own re-registration supersedes its prior one.
                if time.monotonic() >= deadline:
                    raise
                time.sleep(conn_backoff_s * jitter)
                conn_backoff_s = min(conn_backoff_s * 2, 5.0)
                continue
            if not (isinstance(reply, dict) and reply.get("busy")):
                break
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    "head deferred registration past the 300s "
                    "admission deadline")
            time.sleep(reply.get("retry_after_ms", 100) / 1000.0 * jitter)
        if isinstance(reply, dict) and reply.get("incarnation"):
            self.incarnation = reply["incarnation"]
        self.raylet.incarnation = self.incarnation
    def stamp(self, payload: dict) -> dict:
        """Stamp a head-bound payload with this registration's fencing
        identity.  ``node_id`` defaults to self (location rows carry
        their own).  Callable mid-construction (the raylet's heartbeat
        thread starts before NodeHost.__init__ finishes): before the
        incarnation arrives the payload goes out unstamped, which the
        head admits — registration itself is what mints the fence."""
        if "node_id" not in payload and hasattr(self, "raylet"):
            payload["node_id"] = self.raylet.node_id.binary()
        if self.incarnation is not None:
            payload["incarnation"] = self.incarnation
        return payload

    def fence_watch(self, cb=None):
        """Async-reply callback that spots ``{"fenced": True}``
        rejections and routes them into :meth:`on_fenced` before
        delegating to ``cb`` (if any)."""
        def on_done(result, err):
            if err is None and isinstance(result, dict) and \
                    result.get("fenced"):
                self.on_fenced(result)
            if cb is not None:
                cb(result, err)

        return on_done

    def on_fenced(self, rejection: dict):
        """The head rejected a message from this incarnation: we are a
        ZOMBIE — declared dead during a partition, now healed.  Drain
        every lease the dead incarnation held (the head's submitters
        already treat them as lost) and re-register as a fresh
        incarnation; the raylet, stores and workers live on."""
        if self.stopped:
            return
        rejected = rejection.get("rejected")
        with self._fence_lock:
            if self._refencing:
                return
            if rejected is not None and self.incarnation is not None and \
                    int(rejected) != int(self.incarnation):
                return   # stale rejection aimed at a previous incarnation
            self._refencing = True
        threading.Thread(
            target=self._drain_and_reregister, daemon=True,
            name=f"ray_tpu::refence::{self.raylet.node_id.hex()[:6]}"
        ).start()

    def _drain_and_reregister(self):
        from ray_tpu._private.debug import flight_recorder, swallow
        try:
            flight_recorder.record(
                "node.fenced", node=self.raylet.node_id.hex()[:12],
                incarnation=self.incarnation)
            with self._workers_lock:
                workers = list(self._workers.values())
                self._workers.clear()
                self._grant_times.clear()
            for worker in workers:
                try:
                    self.raylet.return_worker(worker, disconnect=True)
                except Exception as e:
                    swallow.noted("node_host.fence_drain", e)
            self._register()
            # The head pruned this node's federation entry at death and
            # our diff base is stale relative to it: resync fully.
            self._metrics_shipper.force_full()
            flight_recorder.record(
                "node.reregistered", node=self.raylet.node_id.hex()[:12],
                incarnation=self.incarnation)
        except Exception as e:
            swallow.noted("node_host.refence", e)
        finally:
            with self._fence_lock:
                self._refencing = False

    # ---- lease / execute ----------------------------------------------
    def _handle_lease(self, spec, reply):
        def on_reply(result):
            worker = result.pop("worker", None)
            result.pop("raylet", None)
            if worker is not None:
                import time
                token = worker.worker_id.binary()
                with self._workers_lock:
                    self._workers[token] = worker
                    self._grant_times[token] = time.monotonic()
                result["worker_token"] = token
                result["node_id"] = self.raylet.node_id.binary()
            reply(result)

        self.raylet.request_worker_lease(spec, on_reply)

    def _handle_lease_batch(self, payload, reply):
        """Batched lease RPC (one round-trip for up to lease_batch_size
        grants): package each granted worker as a lease token exactly
        like the single-lease handler; spillback/backlog/reject entries
        pass through untouched."""
        import time

        def on_reply(result):
            out = []
            for r in result.get("results") or []:
                worker = r.pop("worker", None)
                r.pop("raylet", None)
                if worker is not None:
                    token = worker.worker_id.binary()
                    with self._workers_lock:
                        self._workers[token] = worker
                        self._grant_times[token] = time.monotonic()
                    r["worker_token"] = token
                    r["node_id"] = self.raylet.node_id.binary()
                out.append(r)
            reply({"results": out})

        self.raylet.request_worker_lease_batch(payload["specs"], on_reply)

    def _worker(self, token: bytes):
        with self._workers_lock:
            return self._workers.get(token)

    @staticmethod
    def _task_reply(spec, err) -> dict:
        """Completion reply; a traced spec drains this process's spans
        onto it so the head's timeline sees the execute side."""
        import pickle
        out = {"error": None if err is None else pickle.dumps(err)}
        if getattr(spec, "trace_ctx", None):
            from ray_tpu.util import tracing
            out["trace"] = tracing.drain()
        return out

    def _handle_push(self, payload, reply):
        import pickle
        worker = self._worker(payload["worker_token"])
        if worker is None:
            reply({"error": pickle.dumps(
                exceptions.WorkerCrashedError("lease token unknown"))})
            return
        spec = payload["spec"]
        worker.push_task(
            spec, lambda err: reply(self._task_reply(spec, err)))

    def _handle_assign_actor(self, payload, reply):
        import pickle
        worker = self._worker(payload["worker_token"])
        if worker is None:
            reply({"error": pickle.dumps(
                exceptions.WorkerCrashedError("lease token unknown"))})
            return
        spec = payload["spec"]
        worker.assign_actor(
            spec, lambda err: reply(self._task_reply(spec, err)))

    def _handle_push_actor_task(self, payload, reply):
        import pickle
        worker = self._worker(payload["worker_token"])
        if worker is None:
            reply({"error": pickle.dumps(exceptions.ActorError(
                reason="actor worker gone"))})
            return
        spec = payload["spec"]
        worker.submit_actor_task(
            spec, lambda err: reply(self._task_reply(spec, err)))

    def _handle_return_worker(self, payload) -> bool:
        token = payload["worker_token"]
        disconnect = payload.get("disconnect", False)
        with self._workers_lock:
            worker = self._workers.pop(token, None)
            self._grant_times.pop(token, None)
        if worker is not None:
            if worker.state == "ACTOR" and not disconnect:
                # Dedicated actor workers keep their lease token alive.
                with self._workers_lock:
                    self._workers[token] = worker
            self.raylet.return_worker(worker, disconnect=disconnect)
        return True

    def _handle_reconcile_leases(self, payload) -> int:
        """Release leased workers whose tokens the head does not hold
        (grant replies lost on a dropped connection — reference
        ReleaseUnusedWorkers, node_manager.proto:312).  Fresh grants are
        exempt (RECONCILE_GRACE_S) — sweeping a grant whose reply is
        concurrently in flight would strand the lease the head is about
        to use; actors are additionally protected by the actor
        manager's creation retry on WorkerCrashedError."""
        import time

        from ray_tpu._private.config import get_config
        held = set(payload.get("held", ()))
        # Grants younger than the grace window are exempt: their reply
        # may still be in flight, so the head legitimately not holding
        # them yet does not mean the reply was lost.  A genuinely
        # leaked token ages past the window and the next reconcile
        # (heads reconcile on every reconnect) sweeps it.
        cutoff = time.monotonic() - get_config().lease_reconcile_grace_s
        with self._workers_lock:
            leaked = [(tok, w) for tok, w in self._workers.items()
                      if tok not in held and
                      self._grant_times.get(tok, 0.0) < cutoff]
            for tok, _w in leaked:
                del self._workers[tok]
                self._grant_times.pop(tok, None)
        for _tok, worker in leaked:
            # An idle grant never ran anything: back to the pool.  A
            # worker in ACTOR state DID run a creation (the reply was
            # lost) — destroy it, or a ghost instance would survive in
            # the pool; the owner's creation retry makes a fresh one.
            self.raylet.return_worker(
                worker,
                disconnect=getattr(worker, "state", "") == "ACTOR")
        return len(leaked)

    # ---- resources / objects ------------------------------------------
    def _handle_update_usage(self, batch) -> bool:
        self.raylet.update_resource_usage(batch)
        return True

    def _handle_fetch_object(self, payload) -> Optional[bytes]:
        oid = ObjectID(payload["object_id"])
        serialized = self.raylet.object_store.get_serialized(oid)
        return None if serialized is None else serialized.to_bytes()

    def _handle_delete_object(self, payload) -> bool:
        self.raylet.object_store.delete(ObjectID(payload["object_id"]))
        return True

    # ---- placement-group 2PC ------------------------------------------
    def _handle_prepare_bundle(self, payload) -> bool:
        return self.raylet.prepare_bundle_resources(
            payload["pg_id"], payload["index"], payload["request"])

    def _handle_commit_bundle(self, payload) -> bool:
        self.raylet.commit_bundle_resources(
            payload["pg_id"], payload["index"], payload["request"])
        return True

    def _handle_cancel_bundle(self, payload) -> bool:
        self.raylet.cancel_resource_reserve(
            payload["pg_id"], payload["index"])
        return True

    # ---- observability shipping ----------------------------------------
    def maybe_ship_observability(self):
        """Ship this daemon's metrics delta and buffered tracing spans
        to the head (piggybacked on the heartbeat loop, throttled, all
        async).  Metrics ride a direct RPC into the head's federation;
        spans ride the batched wire publisher into the GCS timeline
        store — the same path task events take."""
        import time

        from ray_tpu._private.config import get_config
        from ray_tpu._private.debug import swallow
        if getattr(self, "raylet", None) is None:
            # The raylet's heartbeat loop fires into this callback from
            # inside the Raylet constructor — before ``self.raylet``
            # is even bound on the host.  Nothing to ship yet.
            return
        now = time.monotonic()
        cfg = get_config()
        # Shared per-beat channel budget (congestion control): the
        # liveness beat already went out un-charged; metrics deltas
        # spend first, timeline spans get the remainder.  An
        # over-budget metrics delta is SHED — not sent, shipper
        # force-fulled so the next admitted report is a full resync
        # (deferral with self-heal, never silent staleness).
        budget = int(cfg.heartbeat_payload_budget_bytes)
        remaining = budget if budget > 0 else None
        interval = cfg.metrics_report_interval_ms / 1000.0
        if now - self._last_metrics_ship >= interval:
            self._last_metrics_ship = now
            try:
                delta, full = self._metrics_shipper.collect_delta()
            except Exception as e:
                # A collector bug must degrade metrics, not heartbeats.
                swallow.noted("node_host.metrics_delta", e)
                delta, full = None, False
            if delta:
                payload = self.stamp(
                    {"node_id": self.raylet.node_id.binary(),
                     "snapshot": delta, "full": full})
                # Heartbeat-channel telemetry (ROADMAP item 1): what
                # does each observability kind cost per beat in bytes?
                # Sized on the delta payload itself — the dominant
                # term; framing overhead is constant per RPC.  This IS
                # a second pickle of the delta (the RPC layer has no
                # frame-size hook), accepted because the metrics beat
                # runs at metrics_report_interval_ms cadence (2s
                # default) with steady-state deltas of a few KB — not
                # a per-task path.  The same size now doubles as the
                # budget charge, so it is computed BEFORE the send.
                size = 0
                try:
                    import pickle
                    size = len(pickle.dumps(payload, protocol=4))
                except Exception as e:
                    swallow.noted("node_host.payload_telemetry", e)
                from ray_tpu._private.metrics_agent import record_internal
                node_hex = self.raylet.node_id.hex()[:12]
                if remaining is not None and size > remaining:
                    # Over budget: shed the delta.  force_full() makes
                    # the next admitted report a resync, so the head
                    # converges once the channel decongests.
                    self.metrics_sheds += 1
                    self._metrics_shipper.force_full()
                    try:
                        record_internal(
                            "ray_tpu.heartbeat.shed_bytes", size,
                            mtype="counter", kind="metrics",
                            node=node_hex)
                    except Exception as e:
                        swallow.noted("node_host.payload_telemetry", e)
                else:
                    if remaining is not None:
                        remaining -= size

                    def on_report(result, err):
                        # Lost or rejected report: the diff base
                        # already counts it as shipped — resync fully
                        # next time so settled series can't stay stale
                        # at the head.
                        if err is not None or result is not True:
                            self._metrics_shipper.force_full()

                    try:
                        record_internal(
                            "ray_tpu.heartbeat.payload_bytes", size,
                            mtype="counter", kind="metrics",
                            node=node_hex)
                    except Exception as e:
                        swallow.noted("node_host.payload_telemetry", e)
                    self.client.call_async(
                        "metrics_report", payload,
                        self.fence_watch(on_report))
        if now - self._last_timeline_ship >= 0.5:
            self._last_timeline_ship = now
            if self._timeline_shipper is None:
                self._timeline_shipper = _TimelineShipper(
                    self.adapter.gcs.publisher.publish,
                    self._timeline_source,
                    self.raylet.node_id.hex()[:12],
                    lambda: self.clock_sync.offset_s)
            try:
                self._timeline_shipper.ship(budget_cap=remaining)
            except Exception as e:
                swallow.noted("node_host.timeline_ship", e)

    def _handle_observability_stats(self, _payload) -> dict:
        ts = self._timeline_shipper
        from ray_tpu._private import worker_pool as wp
        return {
            "metrics_sheds": self.metrics_sheds,
            "timeline_shipped_bytes": ts.shipped_bytes if ts else 0,
            "timeline_shipped_batches": ts.shipped_batches if ts else 0,
            "timeline_windows_shed": ts.windows_shed if ts else 0,
            "timeline_dropped": ts.dropped if ts else 0,
            "worker_startup_throttled": wp.global_startup_throttled(),
        }

    @property
    def _timeline_source(self) -> str:
        return f"node-{self.raylet.node_id.hex()[:12]}"

    # ---- debug plane ---------------------------------------------------
    def _handle_arm_fault(self, payload) -> bool:
        fault_injection.arm(
            payload["point"], payload.get("mode", "error"),
            count=int(payload.get("count", 1)),
            skip=int(payload.get("skip", 0)),
            delay_s=float(payload.get("delay_s", 0.0)),
            match=payload.get("match"))
        return True

    def _handle_disarm_fault(self, payload) -> bool:
        fault_injection.disarm(payload.get("point"),
                               match=payload.get("match"))
        return True

    def _make_wedge_listener(self):
        def on_wedge(event: str, report: dict):
            if self.stopped:
                return
            try:
                self.client.call_async(
                    "wedge_report",
                    self.stamp({"node_id": self.raylet.node_id.binary(),
                                "event": event, "report": report}),
                    self.fence_watch())
            except Exception as e:
                from ray_tpu._private.debug import swallow
                swallow.noted("node_host.wedge_ship", e)

        return on_wedge

    # ---- lifecycle -----------------------------------------------------
    def _handle_stop(self, _payload) -> bool:
        self._stop_event.set()
        return True

    def wait(self):
        self._stop_event.wait()
        self.shutdown()

    def shutdown(self):
        self.stopped = True
        self._stop_event.set()
        try:
            from ray_tpu._private.debug import watchdog as watchdog_mod
            watchdog_mod.remove_listener(self._wedge_listener)
            # Clean shutdown: this process's wedge/crash files have
            # been shipped to the head already — drop them so 64 hosts
            # cycling under chaos can't grow <temp_dir>/wedges forever.
            watchdog_mod.prune_own_crash_files()
        except Exception:
            pass
        try:
            self.adapter.gcs.task_events.stop()
        except Exception:
            pass
        try:
            self.raylet.shutdown()
        except Exception:
            pass
        self.peers.close_all()
        self.server.stop()
        self.client.close()


def main(argv=None):
    """``python -m ray_tpu._private.node_host --head HOST:PORT`` — the
    daemon entry (reference: ``src/ray/raylet/main.cc``)."""
    import argparse
    import json

    parser = argparse.ArgumentParser(prog="ray_tpu.node_host")
    parser.add_argument("--head", required=True,
                        help="head service address, host:port")
    parser.add_argument("--resources", default="{}",
                        help="JSON dict of total resources")
    parser.add_argument("--name", default="", help="node name")
    parser.add_argument("--reg-token", default="",
                        help="one-shot token the spawner matches the "
                             "registration against")
    parser.add_argument("--system-config", default="",
                        help="JSON config propagated from the head "
                             "(RayConfig::initialize parity)")
    args = parser.parse_args(argv)
    if args.system_config:
        from ray_tpu._private.config import initialize_config
        initialize_config(json.loads(args.system_config))
    from ray_tpu._private.config import get_config
    if get_config().tracing_enabled:
        # A traced head traces its daemons too: tick/spill/transfer
        # spans recorded here ship to the GCS timeline store.
        from ray_tpu.util import tracing
        tracing.enable()
    host, _, port = args.head.rpartition(":")
    node = NodeHost((host, int(port)), json.loads(args.resources),
                    node_name=args.name, reg_token=args.reg_token)
    node.wait()


if __name__ == "__main__":
    main()
