"""Driver bootstrap: the global worker + init/shutdown.

Parity: reference ``python/ray/worker.py`` — ``init`` (:683) starts/connects
the cluster (head path: Redis -> GCS -> raylet -> monitor -> dashboard,
node.py:1064; here: GcsServer + head Raylet + driver CoreWorker),
``shutdown``, the global-worker singleton, and the public
``get/put/wait/kill/cancel/get_actor`` entry points re-exported from
``ray_tpu/__init__.py``.
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, List, Optional, Sequence, Tuple

from ray_tpu import exceptions
from ray_tpu._private import worker_context
from ray_tpu._private.config import get_config, initialize_config
from ray_tpu._private.core_worker import CoreWorker
from ray_tpu._private.ids import JobID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.debug import diag_rlock


class Worker:
    """The per-process global worker (driver side)."""

    def __init__(self):
        self.connected = False
        self.cluster = None
        self.core_worker: Optional[CoreWorker] = None
        self.job_id: Optional[JobID] = None
        self.namespace: str = ""
        self.mode: Optional[str] = None
        self.client_connection = None    # set in remote-driver mode


_global_worker: Optional[Worker] = None
_init_lock = diag_rlock("worker._init_lock")


def global_worker() -> Worker:
    global _global_worker
    with _init_lock:
        if _global_worker is None:
            _global_worker = Worker()
        return _global_worker


def global_worker_or_none() -> Optional[Worker]:
    return _global_worker


def init(address: Optional[str] = None, *, num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None, num_gpus: Optional[float] = None,
         resources: Optional[dict] = None, object_store_memory: Optional[int] = None,
         namespace: str = "", job_config: Optional[dict] = None,
         ignore_reinit_error: bool = False, _system_config: Optional[dict] = None,
         _cluster=None, **kwargs):
    """Start (or connect to) a cluster and attach this driver.

    ``address=None`` starts a new in-process cluster with one head node
    (reference head path, worker.py:683 + node.py:1064).  ``_cluster``
    attaches to an existing :class:`ray_tpu._private.cluster.Cluster`
    (cluster_utils test path).
    """
    w = global_worker()
    with _init_lock:
        if w.connected:
            if ignore_reinit_error:
                return RuntimeContextInfo(w)
            raise RuntimeError("ray_tpu.init() called twice; pass "
                              "ignore_reinit_error=True to ignore.")
        initialize_config(_system_config)
        if get_config().tracing_enabled:
            from ray_tpu.util import tracing
            tracing.enable()
        if address and str(address).startswith("ray-tpu://"):
            # Remote-driver path (Ray Client parity): connect to a
            # running head's wire service and drive it from here.
            from ray_tpu._private import client_runtime
            from ray_tpu.rpc import RpcClient
            host, _, port = address[len("ray-tpu://"):].rpartition(":")
            w.client_connection = RpcClient((host, int(port)))
            client_runtime.install(w.client_connection)
            w.namespace = namespace or w.namespace
            if get_config().log_to_driver:
                # Worker log lines reach the remote driver over the
                # long-poll batched pubsub (one outstanding poll).
                from ray_tpu._private.log_monitor import LOG_CHANNEL
                from ray_tpu.gcs.wire_pubsub import SubscriberClient
                from ray_tpu._private import log_monitor as lm
                sub = SubscriberClient(w.client_connection)
                sub.subscribe(LOG_CHANNEL, None,
                              lm.make_log_mirror_callback())
                w.client_log_sub = sub
            atexit.register(_atexit_shutdown)
            return RuntimeContextInfo(w)
        from ray_tpu._private.cluster import Cluster
        if _cluster is not None:
            cluster = _cluster
        else:
            if num_tpus is None:
                num_tpus = _detect_tpu_chips()
            head_args = dict(num_cpus=num_cpus, num_tpus=num_tpus or 0,
                             num_gpus=num_gpus or 0,
                             object_store_memory=object_store_memory,
                             resources=resources, node_name="head")
            cluster = Cluster(initialize_head=True, head_node_args=head_args)
        w.cluster = cluster
        w.job_id = JobID.next()
        w.namespace = namespace or f"anon_ns_{w.job_id.hex()}"
        w.core_worker = CoreWorker(cluster, w.job_id, is_driver=True)
        cluster.attach_core_worker(w.core_worker)
        cluster.gcs.job_manager.add_job(w.job_id, job_config)
        w.connected = True
        w.mode = "local" if _cluster is None else "cluster"
        if get_config().log_to_driver:
            # print()s inside process-mode workers (local or on remote
            # NodeHosts) surface on this terminal, reference
            # log_to_driver behavior.
            from ray_tpu._private.log_monitor import mirror_worker_logs
            w.log_mirror_sub = mirror_worker_logs(cluster.gcs.publisher)
        if get_config().worker_process_mode == "process" and \
                cluster.head_node is not None:
            # Hide OS-process spawn latency behind init (reference:
            # PrestartWorkers on driver start, worker_pool.h:350).
            total = cluster.head_node.local_resources.to_float_dict("total")
            cluster.head_node.worker_pool.prestart_workers(
                min(int(total.get("CPU", 1)), 8))
        atexit.register(_atexit_shutdown)
        return RuntimeContextInfo(w)


def shutdown():
    w = global_worker_or_none()
    if w is None or not w.connected:
        return
    with _init_lock:
        if w.mode == "client":
            sub = getattr(w, "client_log_sub", None)
            if sub is not None:
                try:
                    sub.close()
                except Exception:
                    pass
                w.client_log_sub = None
            try:
                w.client_connection.close()
            except Exception:
                pass
            w.connected = False
            w.cluster = None
            w.core_worker = None
            w.client_connection = None
            worker_context.clear_context()
            return
        if w.job_id is not None:
            try:
                w.cluster.gcs.job_manager.mark_job_finished(w.job_id)
            except Exception:
                pass
        try:
            w.core_worker.reference_counter.close()
        except Exception:
            pass
        try:
            w.cluster.shutdown()
        except Exception:
            pass
        w.connected = False
        w.cluster = None
        w.core_worker = None
        worker_context.clear_context()
        # Reset scheduling-class interning between clusters to keep ids
        # stable in long test sessions.


def _atexit_shutdown():
    try:
        shutdown()
    except Exception:
        pass


def is_initialized() -> bool:
    w = global_worker_or_none()
    return bool(w and w.connected)


def _require_connected() -> Worker:
    """get/put/wait/kill require an initialized cluster (reference:
    "ray.init has not been called yet" RayConnectionError). No auto-init
    here: a background thread (e.g. an actor-pool reaper) touching the
    API after shutdown() must not silently boot a fresh cluster — that
    leaves connected=True and breaks the next init()."""
    w = global_worker()
    if not w.connected:
        raise RuntimeError(
            "ray_tpu.init() has not been called yet (or the cluster was "
            "shut down); call ray_tpu.init() first.")
    return w


def _detect_tpu_chips() -> float:
    """TPU chips on this host.

    Never *initializes* a jax backend here — first backend init on a real
    TPU can take tens of seconds and must not sit on the ``init()`` path.
    Counted only from env (``RAY_TPU_CHIPS``) or from an
    already-initialized jax backend.
    """
    import os
    import sys
    if "RAY_TPU_CHIPS" in os.environ:
        return float(os.environ["RAY_TPU_CHIPS"])
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            from jax._src import xla_bridge
            if getattr(xla_bridge, "_backends", None):
                return float(len([d for d in jax.devices()
                                  if d.platform != "cpu"]))
        except Exception:
            return 0.0
    return 0.0


class RuntimeContextInfo:
    """Return value of init(): address info (client context parity)."""

    def __init__(self, worker: Worker):
        head = getattr(worker.cluster, "head_node", None) \
            if worker.cluster else None
        node_id = getattr(head, "node_id", None)
        self.address_info = {
            "node_id": node_id.hex() if node_id is not None else None,
            "namespace": worker.namespace,
        }

    def __getitem__(self, k):
        return self.address_info[k]

    def __enter__(self):
        return self

    def __exit__(self, *a):
        shutdown()


# ---------------------------------------------------------------------------
# Public API bodies (re-exported by ray_tpu/__init__.py).
# ---------------------------------------------------------------------------

def get(refs, timeout: Optional[float] = None):
    w = _require_connected()
    if isinstance(refs, ObjectRef):
        return w.core_worker.get([refs], timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")
    return w.core_worker.get(list(refs), timeout)


def put(value) -> ObjectRef:
    w = _require_connected()
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed.")
    return w.core_worker.put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None,
         fetch_local: bool = True) -> Tuple[List, List]:
    w = _require_connected()
    refs = list(refs)
    if any(not isinstance(r, ObjectRef) for r in refs):
        raise TypeError("wait() expects a list of ObjectRefs")
    if len(set(refs)) != len(refs):
        raise ValueError("wait() expects unique ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns > number of refs")
    return w.core_worker.wait(refs, num_returns, timeout, fetch_local)


def kill(actor, *, no_restart: bool = True):
    from ray_tpu.actor import ActorHandle
    w = _require_connected()
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    w.cluster.gcs.actor_manager.destroy_actor(actor._actor_id,
                                              no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Best-effort task cancellation (core_worker.cc Cancel parity).

    Queued tasks are dequeued and failed with TaskCancelledError; a task
    already running on a worker thread cannot be preempted (threads, not
    processes) — it is marked so its result is discarded.
    """
    w = _require_connected()
    task_id = ref.task_id()
    tm = w.core_worker.task_manager
    spec = tm.get_spec(task_id)
    if spec is None or not tm.is_pending(task_id):
        return
    tm.fail_task(spec, exceptions.TaskCancelledError(task_id))


def get_actor(name: str, namespace: Optional[str] = None):
    from ray_tpu.actor import ActorHandle
    w = _require_connected()
    ns = namespace if namespace is not None else w.namespace
    actor = w.cluster.gcs.actor_manager.get_named_actor(name, ns)
    if actor is None:
        raise ValueError(f"Failed to look up actor {name!r} in namespace "
                         f"{ns!r}")
    return ActorHandle._from_gcs_actor(actor)


def get_gpu_ids():
    return []


def get_tpu_ids():
    ctx = worker_context.current_task_spec()
    if ctx is None:
        return []
    n = int(ctx.resources.get("TPU"))
    return list(range(n))


def nodes() -> List[dict]:
    w = _require_connected()
    out = []
    for node_id, info in w.cluster.gcs.node_manager.get_all_node_info().items():
        entry = dict(info)
        entry["NodeID"] = node_id.hex()
        entry["Alive"] = info.get("state") == "ALIVE"
        entry["Resources"] = info.get("info", info).get("resources", {}) \
            if "info" in info else info.get("resources", {})
        out.append(entry)
    return out


def cluster_resources() -> dict:
    w = _require_connected()
    return w.cluster.gcs.resource_manager.view.total_cluster_resources()


def available_resources() -> dict:
    w = _require_connected()
    return w.cluster.gcs.resource_manager.live_available_resources()


def timeline(job=None, critical_path: bool = False) -> list:
    """Merged chrome://tracing dump for the whole cluster: this
    process's spans plus clock-normalized span batches every remote
    daemon shipped to the GCS timeline store.  ``job`` filters to one
    job's spans; ``critical_path`` overlays that job's critical path
    as flow events (``ray-tpu profile`` in trace form)."""
    w = _require_connected()
    from ray_tpu.gcs.timeline import merged_timeline
    return merged_timeline(w.cluster, job=job, critical_path=critical_path)
