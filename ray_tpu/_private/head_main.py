"""Head daemon: ``python -m ray_tpu._private.head_main`` — the
operator-facing cluster entry.

Parity: reference head startup (``python/ray/_private/node.py:1064``
``start_head_processes``: GCS + raylet + monitor + job machinery in one
bring-up, driven by ``ray start --head``, ``scripts.py``).  Here one
process hosts the GCS, the head raylet, the wire service worker-hosts
join, and the JobManager; the CLI talks to all of it over the framed
RPC.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import threading


DEFAULT_ADDRESS_FILE = "/tmp/ray_tpu/head_address"


def register_operator_handlers(cluster, job_manager):
    """Expose job + status surfaces on the head's RPC server (reference:
    the dashboard job REST head + ``ray status``'s GCS queries)."""
    from dataclasses import asdict

    from ray_tpu._private import runtime_env as runtime_env_mod

    server = cluster.head_service.server

    def handle_submit(payload):
        runtime_env = dict(payload.get("runtime_env") or {})
        zip_blob = payload.get("working_dir_zip")
        if zip_blob:
            # Client-side packaged working_dir: store into the KV and
            # reference it by URI (packaging.py upload parity).
            import hashlib
            digest = hashlib.sha256(zip_blob).hexdigest()[:20]
            cluster.gcs.kv.put(runtime_env_mod._PKG_PREFIX + digest.encode(),
                               zip_blob, overwrite=False)
            runtime_env["working_dir"] = f"pkg://{digest}"
        return job_manager.submit_job(
            payload["entrypoint"], runtime_env=runtime_env or None,
            submission_id=payload.get("submission_id"),
            metadata=payload.get("metadata"))

    def handle_cluster_status(_payload):
        nodes = []
        for node_id, info in \
                cluster.gcs.node_manager.get_all_node_info().items():
            nodes.append({"node_id": node_id.hex(),
                          "name": info.get("node_name", ""),
                          "state": info.get("state"),
                          "incarnation": info.get("incarnation", 0),
                          "resources": info.get("resources", {})})
        view = cluster.gcs.resource_manager.view
        return {
            "nodes": nodes,
            "total": view.total_cluster_resources(),
            "available": view.available_cluster_resources(),
            "jobs": [asdict(j) for j in job_manager.list_jobs()],
        }

    server.register("submit_job", handle_submit)
    server.register("job_status", job_manager.get_job_status)
    server.register("job_info",
                    lambda sid: (lambda i: None if i is None else asdict(i))(
                        job_manager.get_job_info(sid)))
    server.register("job_logs", job_manager.get_job_logs)
    server.register("list_jobs",
                    lambda _p: [asdict(j) for j in job_manager.list_jobs()])
    server.register("stop_job", job_manager.stop_job)
    server.register("cluster_status", handle_cluster_status)

    def handle_memory_summary(_payload):
        """Per-node object store stats (reference `ray memory`)."""
        out = []
        for raylet in cluster.raylets():
            store = getattr(raylet, "object_store", None)
            if store is None or not hasattr(store, "used_bytes"):
                continue
            out.append({
                "node": getattr(raylet, "node_name", "") or
                raylet.node_id.hex()[:12],
                "used_bytes": store.used_bytes(),
                "capacity_bytes": getattr(store, "capacity", 0),
                "num_objects": store.num_objects(),
                "stats": dict(getattr(store, "stats", {})),
            })
        return out

    def handle_timeline(payload):
        from ray_tpu.gcs.timeline import merged_timeline
        payload = payload or {}
        return merged_timeline(
            cluster, job=payload.get("job"),
            critical_path=bool(payload.get("critical_path")))

    def handle_profile(payload):
        """Causal job profile (`ray-tpu profile <job>`): critical-path
        walk of the job's task DAG with stage/node/edge attribution."""
        from ray_tpu.experimental.state import api as state_api
        payload = payload or {}
        return state_api.profile_job_from_cluster(
            cluster, payload.get("job"),
            top_k=int(payload.get("top_k", 3)))

    def handle_latency(_payload):
        """Dispatch-latency decomposition (`ray-tpu latency`)."""
        from ray_tpu.gcs.task_events import flushed_manager
        mgr = flushed_manager(cluster.gcs)
        return mgr.latency_summary() if mgr is not None else {}

    def handle_state_list(payload):
        """State API over the wire (`ray-tpu list <resource>`)."""
        from ray_tpu.experimental.state import api as state_api
        resource = payload.get("resource")
        fns = {"tasks": state_api.tasks_from_cluster,
               "actors": state_api.actors_from_cluster,
               "objects": state_api.objects_from_cluster,
               "nodes": state_api.nodes_from_cluster}
        fn = fns.get(resource)
        if fn is None:
            raise ValueError(f"unknown state resource {resource!r}; "
                             f"expected one of {sorted(fns)}")
        filters = [tuple(f) for f in payload.get("filters") or []]
        return fn(cluster, filters or None,
                  payload.get("limit"), payload.get("offset", 0))

    def handle_state_summary(_payload):
        from ray_tpu.experimental.state import api as state_api
        return state_api.summarize_tasks_from_cluster(cluster)

    server.register("memory_summary", handle_memory_summary)
    server.register("timeline_dump", handle_timeline)
    server.register("profile_job", handle_profile)
    server.register("latency_summary", handle_latency)
    server.register("state_list", handle_state_list)
    server.register("state_summary", handle_state_summary)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="ray_tpu.head")
    parser.add_argument("--port", type=int, default=0,
                        help="wire-service port (0 = ephemeral)")
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    parser.add_argument("--resources", default="{}",
                        help="JSON dict of extra head resources")
    parser.add_argument("--address-file", default=DEFAULT_ADDRESS_FILE,
                        help="where to write host:port for the CLI")
    parser.add_argument("--system-config", default="")
    parser.add_argument("--dashboard-port", type=int, default=0,
                        help="REST/metrics dashboard port (0 = ephemeral)")
    args = parser.parse_args(argv)

    import ray_tpu
    from ray_tpu._private.worker import global_worker
    from ray_tpu.job_submission import JobManager

    system_config = json.loads(args.system_config) \
        if args.system_config else None
    ray_tpu.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                 resources=json.loads(args.resources),
                 _system_config=system_config)
    cluster = global_worker().cluster
    host, port = cluster.start_head_service(port=args.port)
    job_manager = JobManager(cluster)
    register_operator_handlers(cluster, job_manager)
    from ray_tpu.dashboard.head import start_dashboard
    dashboard = start_dashboard(cluster, job_manager,
                                port=args.dashboard_port)

    stop = threading.Event()
    cluster.head_service.server.register(
        "shutdown_head", lambda _p: (stop.set(), True)[1])
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_a: stop.set())

    os.makedirs(os.path.dirname(args.address_file), exist_ok=True)
    with open(args.address_file, "w") as f:
        f.write(f"{host}:{port}")
    print(f"ray_tpu head listening on {host}:{port} "
          f"(address file: {args.address_file})", flush=True)
    if dashboard is not None:
        print(f"dashboard at {dashboard.url}", flush=True)
    stop.wait()
    if dashboard is not None:
        dashboard.stop()
    job_manager.shutdown()
    ray_tpu.shutdown()
    try:
        os.unlink(args.address_file)
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
