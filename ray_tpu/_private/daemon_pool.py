"""Daemon thread pool: bounded workers that never block process exit.

``concurrent.futures.ThreadPoolExecutor`` threads are non-daemon and are
joined at interpreter shutdown — one handler blocked in a long wait
would hang the process forever.  Server dispatch and object-plane
transfers instead run on these daemon workers (the reference's io
contexts are likewise detached from process teardown).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

from ray_tpu._private.debug import swallow


class DaemonPool:
    def __init__(self, max_workers: int, name: str = "pool"):
        self._queue: "queue.Queue" = queue.Queue()
        self._stopped = threading.Event()
        self._threads = []
        for i in range(max_workers):
            t = threading.Thread(target=self._loop, daemon=True,
                                 name=f"{name}::{i}")
            t.start()
            self._threads.append(t)

    def submit(self, fn: Callable, *args):
        if self._stopped.is_set():
            raise RuntimeError("pool stopped")
        self._queue.put((fn, args))

    def _loop(self):
        while not self._stopped.is_set():
            try:
                fn, args = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                fn(*args)
            except Exception as e:
                # Dispatch errors are the callee's to report — but the
                # pump must not eat the evidence (graftcheck R7): count
                # per site, log the first traceback.
                swallow.noted("daemon_pool.dispatch", e)

    def stop(self):
        self._stopped.set()
