"""Instrumented event loops — the asio substrate equivalent.

Parity: reference ``src/ray/common/asio/`` (boost::asio io_context per daemon
with periodic timers and post()ed handlers, instrumented with per-handler
stats).  Here an event loop is a thread + monotonic timer heap; stats are
kept per handler name for the debug dump (scheduler_stats.cc parity).
"""

from __future__ import annotations

import heapq
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from ray_tpu._private.debug import diag_condition, thread_registry


class EventLoop:
    def __init__(self, name: str = "loop"):
        self.name = name
        self._cond = diag_condition(name="EventLoop._cond")
        self._queue = []            # immediate handlers
        self._timers = []           # (deadline, seq, period, name, fn)
        self._seq = 0
        self._stopped = False
        self.handler_stats: Dict[str, dict] = {}
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ray_tpu::{name}")
        self._thread.start()

    def post(self, fn: Callable, name: str = "anon"):
        with self._cond:
            if self._stopped:
                return
            self._queue.append((name, fn))
            self._cond.notify()

    def schedule_every(self, period_s: float, fn: Callable, name: str):
        """Periodic timer; rescheduled after each run (asio PeriodicalRunner)."""
        with self._cond:
            if self._stopped:
                return
            self._seq += 1
            heapq.heappush(self._timers,
                           (time.monotonic() + period_s, self._seq,
                            period_s, name, fn))
            self._cond.notify()

    def schedule_after(self, delay_s: float, fn: Callable, name: str = "timer"):
        with self._cond:
            if self._stopped:
                return
            self._seq += 1
            heapq.heappush(self._timers,
                           (time.monotonic() + delay_s, self._seq,
                            None, name, fn))
            self._cond.notify()

    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify()
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=2)

    def _record(self, name: str, elapsed: float):
        st = self.handler_stats.setdefault(
            name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        st["count"] += 1
        st["total_s"] += elapsed
        st["max_s"] = max(st["max_s"], elapsed)

    def _run(self):
        # Loop-affinity identity (@loop_only runtime checks): this thread
        # IS the "<kind>" loop for kind = name up to the node-id suffix.
        # Unregistered on exit — thread idents are reused by the OS, and
        # a stale entry would let a later unrelated thread impersonate
        # a dead loop.
        thread_registry.register_current(self.name)
        try:
            self._run_inner()
        finally:
            thread_registry.unregister_current()

    def _run_inner(self):
        while True:
            fn = None
            name = None
            with self._cond:
                while not self._stopped:
                    now = time.monotonic()
                    if self._queue:
                        name, fn = self._queue.pop(0)
                        break
                    if self._timers and self._timers[0][0] <= now:
                        deadline, seq, period, name, fn = heapq.heappop(
                            self._timers)
                        if period is not None:
                            self._seq += 1
                            heapq.heappush(
                                self._timers,
                                (now + period, self._seq, period, name, fn))
                        break
                    timeout = None
                    if self._timers:
                        timeout = max(0.0, self._timers[0][0] - now)
                    self._cond.wait(timeout=timeout)
                if self._stopped:
                    return
            t0 = time.monotonic()
            try:
                fn()
            except Exception:
                traceback.print_exc()
            self._record(name, time.monotonic() - t0)
