"""Instrumented event loops — the asio substrate equivalent.

Parity: reference ``src/ray/common/asio/`` (boost::asio io_context per daemon
with periodic timers and post()ed handlers, instrumented with per-handler
stats).  Here an event loop is a thread + monotonic timer heap; stats are
kept per handler name for the debug dump (scheduler_stats.cc parity).

Introspection plane (ISSUE 13): every loop registers a watchdog beat
(stall detection + wedge reports), measures post-to-run lag and the
slowest handler, and exports ``handler_stats`` — previously an orphaned
in-memory dict — plus the lag/slowest gauges as /metrics series through
a scrape-time collector.
"""

from __future__ import annotations

import heapq
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from ray_tpu._private import fault_injection
from ray_tpu._private.debug import (diag_condition, thread_registry,
                                    watchdog)


class EventLoop:
    def __init__(self, name: str = "loop"):
        self.name = name
        self._cond = diag_condition(name="EventLoop._cond")
        self._queue = []            # (name, fn, t_posted) immediate handlers
        self._timers = []           # (deadline, seq, period, name, fn)
        self._seq = 0
        self._stopped = False
        self.handler_stats: Dict[str, dict] = {}
        # Post-to-run lag (how long a posted handler waited for the
        # loop thread) + slowest-handler tracking: plain attribute
        # accumulation on the loop thread, rendered by the collector.
        self.lag_count = 0
        self.lag_sum_s = 0.0
        self.lag_max_s = 0.0
        self.slowest_handler = ""
        self.slowest_handler_s = 0.0
        self._beat = watchdog.register(
            name, kind="loop",
            queue_depth=lambda: len(self._queue),
            stats=self._beat_stats)
        self._register_metrics()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ray_tpu::{name}")
        self._thread.start()

    def _beat_stats(self) -> dict:
        return {
            "lag_max_s": round(self.lag_max_s, 6),
            "lag_mean_s": round(self.lag_sum_s / self.lag_count, 6)
            if self.lag_count else 0.0,
            "slowest_handler": self.slowest_handler,
            "slowest_handler_s": round(self.slowest_handler_s, 6),
        }

    def _register_metrics(self):
        """Export this loop's per-handler stats + lag gauges at /metrics
        (scrape-time collector: zero cost on the handler path, series
        pruned when the loop is collected)."""
        try:
            from ray_tpu._private.metrics_agent import (
                get_metrics_registry, record_internal)
        except Exception:       # early-bootstrap import failure
            return

        def _collect(loop):
            label = {"loop": loop.name}
            for handler, st in list(loop.handler_stats.items()):
                hl = dict(label, handler=handler)
                # Cumulative values exported as gauges (set, not inc):
                # a scrape-time collector re-runs per exposition and a
                # counter-typed inc would double-count every scrape.
                record_internal("ray_tpu.event_loop.handler_count",
                                st["count"], **hl)
                record_internal("ray_tpu.event_loop.handler_total_s",
                                st["total_s"], **hl)
                record_internal("ray_tpu.event_loop.handler_max_s",
                                st["max_s"], **hl)
            record_internal("ray_tpu.event_loop.queue_depth",
                            len(loop._queue), **label)
            record_internal("ray_tpu.event_loop.lag_max_s",
                            loop.lag_max_s, **label)
            record_internal(
                "ray_tpu.event_loop.lag_mean_s",
                loop.lag_sum_s / loop.lag_count if loop.lag_count
                else 0.0, **label)
            record_internal("ray_tpu.event_loop.slowest_handler_s",
                            loop.slowest_handler_s, **label)

        get_metrics_registry().register_collector(self, _collect)

    def post(self, fn: Callable, name: str = "anon"):
        with self._cond:
            if self._stopped:
                return
            self._queue.append((name, fn, time.monotonic()))
            self._cond.notify()

    def schedule_every(self, period_s: float, fn: Callable, name: str):
        """Periodic timer; rescheduled after each run (asio PeriodicalRunner)."""
        with self._cond:
            if self._stopped:
                return
            self._seq += 1
            heapq.heappush(self._timers,
                           (time.monotonic() + period_s, self._seq,
                            period_s, name, fn))
            self._cond.notify()

    def schedule_after(self, delay_s: float, fn: Callable, name: str = "timer"):
        with self._cond:
            if self._stopped:
                return
            self._seq += 1
            heapq.heappush(self._timers,
                           (time.monotonic() + delay_s, self._seq,
                            None, name, fn))
            self._cond.notify()

    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify()
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=2)

    def _record(self, name: str, elapsed: float):
        st = self.handler_stats.setdefault(
            name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        st["count"] += 1
        st["total_s"] += elapsed
        st["max_s"] = max(st["max_s"], elapsed)
        if elapsed > self.slowest_handler_s:
            self.slowest_handler_s = elapsed
            self.slowest_handler = name

    def _run(self):
        # Loop-affinity identity (@loop_only runtime checks): this thread
        # IS the "<kind>" loop for kind = name up to the node-id suffix.
        # Unregistered on exit — thread idents are reused by the OS, and
        # a stale entry would let a later unrelated thread impersonate
        # a dead loop.
        thread_registry.register_current(self.name)
        try:
            self._run_inner()
        finally:
            thread_registry.unregister_current()
            watchdog.unregister(self._beat)

    def _run_inner(self):
        while True:
            fn = None
            name = None
            posted_at = None
            with self._cond:
                while not self._stopped:
                    now = time.monotonic()
                    if self._queue:
                        name, fn, posted_at = self._queue.pop(0)
                        break
                    if self._timers and self._timers[0][0] <= now:
                        deadline, seq, period, name, fn = heapq.heappop(
                            self._timers)
                        if period is not None:
                            self._seq += 1
                            heapq.heappush(
                                self._timers,
                                (now + period, self._seq, period, name, fn))
                        break
                    timeout = None
                    if self._timers:
                        timeout = max(0.0, self._timers[0][0] - now)
                    self._cond.wait(timeout=timeout)
                if self._stopped:
                    return
            t0 = time.monotonic()
            if posted_at is not None:
                # Post-to-run lag: how long the handler sat behind the
                # GIL / earlier handlers — the startup-stage tail PR 11
                # could not attribute.
                lag = t0 - posted_at
                self.lag_count += 1
                self.lag_sum_s += lag
                if lag > self.lag_max_s:
                    self.lag_max_s = lag
            self._beat.begin(name)
            try:
                # Fault point ``loop.stall``: delay mode wedges THIS
                # loop mid-handler — the deterministic watchdog drill.
                fault_injection.hook("loop.stall")
                fn()
            except Exception:
                traceback.print_exc()
            finally:
                self._beat.end()
            self._record(name, time.monotonic() - t0)
