"""Per-node worker pool.

Parity: reference ``src/ray/raylet/worker_pool.{h,cc}`` — pool of
pre-startable workers, ``PopWorker`` (worker_pool.h:338) /
``PushWorker`` return, ``PrestartWorkers`` (:350), idle soft-cap with
eviction (ray_config_def.h:129), dedicated workers for actors.

Two worker modes behind one lease lifecycle
(``worker_process_mode`` config):

* ``thread`` (default) — workers are threads in the node's process.
  One process per host owns the TPU chips (XLA requires single
  ownership), so Python-level parallelism comes from threads — jax
  compiled computations release the GIL, and framework logic is
  IO-bound.
* ``process`` — workers are real OS processes
  (``python -m ray_tpu._private.worker_main``), spawned like the
  reference's ``StartWorkerProcess`` (worker_pool.h:428): the child
  registers back over a framed-RPC socket (``WorkerHostService``) and
  tasks are pushed to its own RPC server (``CoreWorkerService.PushTask``
  parity, core_worker.proto:353) — every task and object crosses a real
  process boundary.

The scheduler and transport layers are identical in both modes.
"""

from __future__ import annotations

import queue
import subprocess
import sys
import threading
import traceback
from typing import Callable, Dict, List, Optional

from ray_tpu import exceptions
from ray_tpu._private import worker_context
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ObjectID, WorkerID
from ray_tpu._private.debug import diag_lock, diag_rlock


class WorkerState:
    IDLE = "IDLE"
    LEASED = "LEASED"
    ACTOR = "ACTOR"
    DEAD = "DEAD"


class Worker:
    """One executor thread; may become dedicated to an actor."""

    runtime_env_hash = ""   # thread workers are universal: the env is
                            # applied per task in the executor

    def __init__(self, pool: "WorkerPool", node):
        self.worker_id = WorkerID.from_random()
        self.node = node
        self.node_id = node.node_id
        self._pool = pool
        self.state = WorkerState.IDLE
        self._queue: "queue.Queue" = queue.Queue()
        self.actor_id = None
        self.actor_instance = None
        self._actor_threads: List[threading.Thread] = []
        self._group_queues: Dict[str, "queue.Queue"] = {}
        self._max_concurrency = 1
        self._killed = threading.Event()
        self._thread = threading.Thread(
            target=self._main_loop, daemon=True,
            name=f"ray_tpu::worker::{self.worker_id.hex()[:8]}")
        self._thread.start()

    # ---- normal task path ----------------------------------------------
    def push_task(self, spec, on_done: Callable):
        """Execute a normal (or actor-creation) task on this worker
        (CoreWorkerService.PushTask parity)."""
        self._queue.put(("task", spec, on_done))

    def assign_actor(self, creation_spec, on_done: Callable):
        """Run the actor creation task; on success this worker is dedicated
        to the actor until death."""
        self._queue.put(("create_actor", creation_spec, on_done))

    def submit_actor_task(self, spec, on_done: Callable):
        """Ordered actor method execution (sequential_actor_submit_queue
        parity; max_concurrency>1 uses the out-of-order queue).  A
        method tagged with a concurrency group routes to that group's
        own pool (concurrency_group_manager.cc)."""
        group = getattr(spec, "concurrency_group", "")
        gq = self._group_queues.get(group) if group else None
        if gq is not None:
            gq.put(("actor_task", spec, on_done))
        else:
            self._queue.put(("actor_task", spec, on_done))

    def kill_actor(self):
        self._killed.set()
        self._queue.put(("exit", None, None))

    def stop(self):
        self._killed.set()
        self._queue.put(("exit", None, None))

    # ---- main loop ------------------------------------------------------
    def _main_loop(self):
        worker_context.set_context(
            worker_context.ExecutionContext(worker=self, node=self.node))
        from ray_tpu._private import executor as executor_mod
        while not self._killed.is_set():
            try:
                kind, spec, on_done = self._queue.get(timeout=1.0)
            except queue.Empty:
                continue
            if kind == "exit":
                break
            try:
                if kind == "create_actor":
                    self._handle_create_actor(spec, on_done, executor_mod)
                elif kind == "actor_task":
                    self._run_actor_task(spec, on_done, executor_mod)
                else:
                    ok, err = executor_mod.execute_task(
                        spec, self.node, self.node.core_worker)
                    on_done(None if ok else err)
            except Exception as e:  # framework error, not user error
                traceback.print_exc()
                if on_done is not None:
                    on_done(exceptions.RayTpuError(str(e)))
            # Drop the frame's bindings: an idle worker must not pin the
            # last spec (its inline args hold live ObjectRefs — keeping
            # them would defer the owner's release indefinitely).
            kind = spec = on_done = None
        self._on_exit()

    def _handle_create_actor(self, spec, on_done, executor_mod):
        ok, result = executor_mod.execute_task(
            spec, self.node, self.node.core_worker)
        if not ok:
            on_done(result)
            return
        self.state = WorkerState.ACTOR
        self.actor_id = spec.actor_id
        self.actor_instance = result
        self._max_concurrency = max(1, spec.max_concurrency)
        if self._max_concurrency > 1:
            for i in range(self._max_concurrency - 1):
                t = threading.Thread(target=self._actor_concurrent_loop,
                                     daemon=True,
                                     name=f"{self._thread.name}::cc{i}")
                t.start()
                self._actor_threads.append(t)
        # Named concurrency groups: each gets its own queue + thread
        # pool, concurrent with the default group and each other
        # (concurrency_group_manager.cc parity).
        for gname, gsize in (spec.concurrency_groups or {}).items():
            gq: "queue.Queue" = queue.Queue()
            self._group_queues[gname] = gq
            for i in range(max(1, int(gsize))):
                t = threading.Thread(
                    target=self._actor_concurrent_loop, args=(gq,),
                    daemon=True,
                    name=f"{self._thread.name}::cg-{gname}-{i}")
                t.start()
                self._actor_threads.append(t)
        on_done(None)

    def _run_actor_task(self, spec, on_done, executor_mod):
        ok, err = executor_mod.execute_task(
            spec, self.node, self.node.core_worker,
            actor_instance=self.actor_instance)
        on_done(None if ok else err)

    def _actor_concurrent_loop(self, source: "queue.Queue" = None):
        worker_context.set_context(
            worker_context.ExecutionContext(worker=self, node=self.node))
        from ray_tpu._private import executor as executor_mod
        src = source if source is not None else self._queue
        while not self._killed.is_set():
            try:
                kind, spec, on_done = src.get(timeout=1.0)
            except queue.Empty:
                continue
            if kind == "exit":
                src.put(("exit", None, None))  # propagate to siblings
                break
            self._run_actor_task(spec, on_done, executor_mod)
            kind, spec, on_done = None, None, None  # no idle-frame pinning

    def _on_exit(self):
        was_actor = self.state == WorkerState.ACTOR
        self.state = WorkerState.DEAD
        self._pool.on_worker_exit(self)
        if was_actor and self.actor_id is not None:
            self.node.on_actor_worker_exit(self.actor_id, self.worker_id)


class WorkerHostService:
    """Raylet-side RPC service that process-mode workers talk to:
    registration handshake, object reads for task args, and function-blob
    fetches from the GCS KV (reference: the raylet socket workers register
    on + plasma UDS + GCS function table, collapsed into one surface)."""

    def __init__(self, node):
        from ray_tpu.rpc import RpcServer
        self._node = node
        self._lock = diag_lock("WorkerHostService._lock")
        self._ports: Dict[str, int] = {}
        self._events: Dict[str, threading.Event] = {}
        self._worker_pins: Dict[str, list] = {}
        self._shm_pins: Dict[str, list] = {}
        # Orders seal against abort: each RPC runs on its own dispatch
        # thread, and abort's locate-then-delete must not interleave
        # with a concurrent seal of the same key (the sealed-object
        # guard would read stale state and delete a live object).
        self._shm_seal_lock = diag_lock("WorkerHostService._shm_seal_lock")
        self.shm_locate_count = 0    # observability/tests
        self.server = RpcServer(
            name=f"workerhost-{node.node_id.hex()[:6]}")
        self.server.register("register_worker", self._register_worker)
        self.server.register("ping", lambda _p: "pong")
        self.server.register("get_object", self._get_object)
        self.server.register("kv_get", self._kv_get)
        # Plasma-client surface (plasma/client.cc parity): metadata over
        # RPC, bytes through the worker's own mmap of the segment.
        self.server.register("shm_info", self._shm_info)
        self.server.register("shm_locate", self._shm_locate)
        self.server.register("shm_release", self._shm_release)
        self.server.register("shm_create", self._shm_create)
        self.server.register("shm_seal", self._shm_seal)
        self.server.register("shm_abort", self._shm_abort)
        # Client-runtime surface: process-mode workers drive the full
        # public API (nested .remote, put/get/wait, actors) through the
        # SAME handlers remote drivers use (client_service.py), with
        # ownership kept by the host's core worker.  Big get_value
        # replies ride chunk sessions.
        from ray_tpu._private.client_service import register_client_surface
        from ray_tpu._private.worker import global_worker_or_none
        from ray_tpu._private.object_store import segment_chunk_source
        from ray_tpu.rpc.chunked import serve_chunks
        self._chunk_server = serve_chunks(
            self.server,
            lambda oid_bin: self._get_object(oid_bin),
            get_source=segment_chunk_source(node.object_store))

        def _namespace():
            w = global_worker_or_none()
            return getattr(w, "namespace", "") if w else ""

        register_client_surface(
            self.server,
            core=self._core,
            kv=node.cluster.gcs.kv,
            actor_manager=lambda: self._node.cluster.gcs.actor_manager,
            node_id_fn=lambda: self._node.node_id,
            namespace_fn=_namespace,
            chunk_server=self._chunk_server,
            pin_cb=self._record_pin)

    @property
    def port(self) -> int:
        return self.server.address[1]

    def wait_for_worker(self, worker_id_hex: str,
                        timeout: float) -> Optional[int]:
        with self._lock:
            ev = self._events.setdefault(worker_id_hex, threading.Event())
        if not ev.wait(timeout=timeout):
            return None
        with self._lock:
            return self._ports.get(worker_id_hex)

    def _register_worker(self, payload) -> bool:
        wid = payload["worker_id"]
        with self._lock:
            self._ports[wid] = payload["port"]
            ev = self._events.setdefault(wid, threading.Event())
        ev.set()
        return True

    def _get_object(self, oid_bin: bytes) -> Optional[bytes]:
        from ray_tpu._private.serialization import SerializedObject
        oid = ObjectID(oid_bin)
        serialized = self._node.object_store.get_serialized(oid)
        if serialized is not None:
            return serialized.to_bytes()
        core = self._node.core_worker
        if core is not None:
            e = core.memory_store.get_entry(oid)
            if e is not None and e.sealed and e.error is None and \
                    isinstance(e.data, SerializedObject):
                return e.data.to_bytes()
        return None

    def _kv_get(self, key: bytes) -> Optional[bytes]:
        return self._node.cluster.gcs.kv.get(key)

    # ---- shm client surface (plasma/client.cc parity) ------------------
    def _native_store(self):
        store = self._node.object_store
        native = getattr(store, "_native", None)
        return store, native

    def _shm_info(self, _payload):
        _store, native = self._native_store()
        if native is None:
            return None
        return {"name": native.name, "capacity": native.capacity}

    def _shm_locate(self, payload):
        """(offset, size) of a sealed object; pins it (store-level AND
        native) against eviction/spill while the worker reads through
        its mapping.  Pin BEFORE reading the offset: native.pin fails
        if the object was just freed, and once it succeeds the block
        cannot move — so the returned (offset, size) can never be
        stale.  The worker releases its pins at the end of every task
        frame (actor calls copy the bytes out first); worker death
        releases whatever a crashed worker still held."""
        store, native = self._native_store()
        if native is None:
            return None
        oid = ObjectID(payload["object_id"])
        entry = store.get(oid)
        from ray_tpu._private.object_store import _NativeHandle
        if entry is None or not isinstance(entry.data, _NativeHandle):
            return None
        store.pin(oid)                       # blocks python-side spill
        if not native.pin(payload["object_id"]):
            store.unpin(oid)                 # freed in the window
            return None
        loc = native.locate(payload["object_id"])
        if loc is None:
            native.unpin(payload["object_id"])
            store.unpin(oid)
            return None
        with self._lock:
            self._shm_pins.setdefault(payload["worker_id"], []).append(oid)
            self.shm_locate_count += 1
        return list(loc)

    def _shm_release(self, payload):
        store, native = self._native_store()
        oid = ObjectID(payload["object_id"])
        with self._lock:
            pins = self._shm_pins.get(payload["worker_id"])
            if not pins or oid not in pins:
                return False      # not pinned by this worker: no-op
            pins.remove(oid)
        store.unpin(oid)
        if native is not None:
            native.unpin(payload["object_id"])
        return True

    def _shm_abort(self, payload):
        """Drop a create-reservation whose write/seal failed — unsealed
        entries are invisible to eviction and would leak forever.

        Reclaims ONLY unsealed reservations: the worker fires abort on
        any mid-write exception, including a timeout on a seal reply
        that actually LANDED host-side — by then the object is sealed,
        registered in the node store and locatable by other readers, so
        deleting it here would corrupt a live object (ADVICE.md)."""
        _store, native = self._native_store()
        if native is None:
            return False
        key = payload["object_id"]
        with self._shm_seal_lock:
            if native.locate(key) is not None:
                return False  # sealed: the seal won the race, keep it
            native.delete(key)
        return True

    def _shm_create(self, payload):
        """Reserve space for a worker-written return value; the worker
        fills the bytes through its own mapping, then shm_seal.  Runs
        the store's eviction-retry reservation (create_request_queue.h
        flow), so a full segment spills LRU victims instead of kicking
        the return onto the socket path."""
        store, native = self._native_store()
        if native is None:
            return None
        return store.reserve_native(ObjectID(payload["object_id"]),
                                    int(payload["size"]))

    def _shm_seal(self, payload):
        """Seal a worker-written object and register it in the node
        store with owner semantics (the big-return path of
        _store_returns, minus the socket copy)."""
        from ray_tpu._private.object_store import InPlasmaMarker
        store, native = self._native_store()
        if native is None:
            return False
        key = payload["object_id"]
        with self._shm_seal_lock:
            if not native.seal(key):
                return False
        oid = ObjectID(key)
        size = int(payload["size"])
        store.register_native_entry(oid, size)
        self._node.cluster.object_directory.add_location(
            oid, self._node.node_id, size=size)
        core = self._node.core_worker
        if core is not None:
            core.memory_store.put(oid, InPlasmaMarker(self._node.node_id))
        return True

    def release_worker_shm_pins(self, worker_id_hex: str):
        store, native = self._native_store()
        with self._lock:
            oids = self._shm_pins.pop(worker_id_hex, [])
        from ray_tpu._private.debug import swallow
        for oid in oids:
            try:
                store.unpin(oid)
                if native is not None:
                    native.unpin(oid.binary())
            except Exception as e:
                # A lost unpin wedges eviction of that object forever.
                swallow.noted("worker_pool.release_shm_pin", e)

    def _core(self):
        core = self._node.core_worker
        if core is None:
            raise RuntimeError("host node has no core worker attached")
        return core

    def _record_pin(self, worker_id_hex: str, object_id):
        with self._lock:
            self._worker_pins.setdefault(worker_id_hex, []).append(
                object_id)

    def release_worker_pins(self, worker_id_hex: str):
        """Drop the put-object pins a (cleanly exited) worker
        accumulated."""
        with self._lock:
            oids = self._worker_pins.pop(worker_id_hex, [])
        core = self._node.core_worker
        if core is None:
            return
        for oid in oids:
            try:
                core.reference_counter.remove_local_ref(oid)
            except Exception:
                pass

    def fail_worker_owned_objects(self, worker_id_hex: str):
        """Owner-death semantics for a CRASHED worker process: objects
        it put are invalidated with OwnerDiedError so borrowers holding
        the refs observe the death instead of ObjectLost-after-timeout
        (reference: reference_count.cc OWNER_DIED; clean exits release
        pins normally via :meth:`release_worker_pins`)."""
        from ray_tpu import exceptions as exc
        with self._lock:
            oids = self._worker_pins.pop(worker_id_hex, [])
        core = self._node.core_worker
        if core is None:
            return
        for oid in oids:
            try:
                core.fail_owned_object(oid, exc.OwnerDiedError(oid))
            except Exception:
                pass

    def stop(self):
        self.server.stop()


class ProcessWorker:
    """A worker living in its own OS process; same interface as Worker.

    Host side of the lease lifecycle: spawns the child (StartWorkerProcess
    parity), waits for its registration on the WorkerHostService, then
    pushes tasks over the child's RPC server and stores the returned
    serialized values with owner semantics."""

    def __init__(self, pool: "WorkerPool", node, runtime_env=None):
        self.worker_id = WorkerID.from_random()
        self.node = node
        self.node_id = node.node_id
        self._pool = pool
        self.state = WorkerState.IDLE
        self.actor_id = None
        self.actor_instance = None      # lives in the child process
        self._max_concurrency = 1
        self._killed = threading.Event()
        self._died_abnormally = False   # crash vs clean stop/cull
        self._queue: "queue.Queue" = queue.Queue()
        self._client = None
        host = pool.host_service()
        import os
        from ray_tpu._private import runtime_env as runtime_env_mod
        self.runtime_env_hash = (runtime_env or {}).get("_hash", "")
        env = dict(os.environ)
        if runtime_env:
            # Materialize working_dir/py_modules host-side, inject env
            # vars + import paths + cwd at spawn (worker_pool.h:428:
            # workers are started FOR an env and keyed by its hash).
            ctx = runtime_env_mod.materialize(
                runtime_env, node.cluster.gcs.kv)
            env = ctx.spawn_env(env)
        env["PYTHONPATH"] = runtime_env_mod.framework_import_root() + \
            os.pathsep + env.get("PYTHONPATH", "")
        # Unbuffered child stdio: prints must reach the tailed log file
        # as they happen, not on 8KB block-buffer flushes at exit.
        env["PYTHONUNBUFFERED"] = "1"
        from ray_tpu._private.config import get_config
        if get_config().tracing_enabled:
            # A traced run traces its process workers too: beyond the
            # forced per-task execute span, spans recorded around it
            # (puts, gets, nested calls) ride the task-reply drain.
            env["RAY_TPU_TRACING"] = "1"
        # Child stdout/stderr land in per-worker session log files; the
        # pool's LogMonitor tails them and streams lines to the driver
        # (reference log_monitor.py + worker stdout redirection).
        from ray_tpu._private import log_monitor as log_monitor_mod
        out_f, err_f = log_monitor_mod.open_worker_log_files(
            self.worker_id.hex())
        pool.ensure_log_monitor()
        try:
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.worker_main",
                 "--host", "127.0.0.1", "--port", str(host.port),
                 "--worker-id", self.worker_id.hex()],
                env=env, stdout=out_f, stderr=err_f)
        finally:
            # The child owns its copies of the fds now.
            out_f.close()
            err_f.close()
        self._pump = threading.Thread(
            target=self._pump_loop, daemon=True,
            name=f"ray_tpu::pworker::{self.worker_id.hex()[:8]}")
        self._pump.start()

    # ---- Worker interface ----------------------------------------------
    def push_task(self, spec, on_done: Callable):
        self._queue.put(("task", spec, on_done))

    def assign_actor(self, creation_spec, on_done: Callable):
        self._queue.put(("create_actor", creation_spec, on_done))

    def submit_actor_task(self, spec, on_done: Callable):
        self._queue.put(("actor_task", spec, on_done))

    def kill_actor(self):
        self.stop()

    def stop(self):
        self._killed.set()
        self._queue.put(("exit", None, None))

        # The pump may be blocked inside a roundtrip for a long-running
        # task; don't leave the OS process orphaned behind it.
        def reap():
            try:
                self._proc.wait(timeout=5.0)
            except Exception:
                try:
                    self._proc.terminate()
                    self._proc.wait(timeout=5.0)
                except Exception:
                    try:
                        self._proc.kill()
                    except Exception:
                        pass

        threading.Thread(target=reap, daemon=True,
                         name="ray_tpu::reap::"
                              + self.worker_id.hex()[:8]).start()

    # ---- pump ----------------------------------------------------------
    def _pump_loop(self):
        from ray_tpu.rpc import RpcClient
        # Generous: on a loaded small box, N concurrently spawned
        # children serialize their interpreter+numpy imports.
        port = self._pool.host_service().wait_for_worker(
            self.worker_id.hex(), timeout=120.0)
        if port is None:
            self._died_abnormally = True
            self._fail_until_exit("worker process failed to register")
            return
        self._client = RpcClient(("127.0.0.1", port))
        while not self._killed.is_set():
            try:
                kind, spec, on_done = self._queue.get(timeout=1.0)
            except queue.Empty:
                # Liveness sweep between pushes: a child that died while
                # idle (crash, OOM-kill) must trigger owner-death
                # handling promptly, not on the next task push.  Drain
                # anything enqueued in the detection window first — an
                # abandoned spec's on_done would otherwise never fire
                # and the submitter's get would hang.
                if self._proc.poll() is not None:
                    self._died_abnormally = True
                    self._killed.set()
                    self._drain_queue_failing(
                        "worker process died while idle")
                    break
                continue
            if kind == "exit":
                break
            if kind == "actor_task" and (
                    self._max_concurrency > 1 or
                    getattr(spec, "concurrency_group", "")):
                # Out-of-order queue parity: up to max_concurrency calls
                # in flight (group-tagged calls bound by their group's
                # semaphore in the child); replies on the client reader.
                self._emit_running(spec)
                fut = self._client.call_future(
                    "push", self._build_payload(kind, spec))
                fut.add_done_callback(
                    lambda f, s=spec, cb=on_done, k=kind:
                    self._on_reply_future(f, s, cb, k))
                continue
            self._roundtrip(kind, spec, on_done)
        self._on_exit()

    def _emit_running(self, spec):
        """Host-side RUNNING transition: the push to the child's RPC
        server is the moment the task starts executing in the worker OS
        process (the child has no path to the GCS event buffer)."""
        from ray_tpu.gcs import task_events
        task_events.emit(self.node.cluster, spec.task_id,
                         task_events.RUNNING,
                         node_id=self.node_id.hex(),
                         worker_id=self.worker_id.hex())

    def _roundtrip(self, kind, spec, on_done):
        self._emit_running(spec)
        try:
            reply = self._client.call("push",
                                      self._build_payload(kind, spec),
                                      timeout=None)
        except Exception as e:
            on_done(exceptions.RayTpuError(
                f"worker process died: {e}"))
            self._died_abnormally = True
            self._killed.set()
            return
        self._handle_reply(reply, spec, on_done, kind)

    def _on_reply_future(self, fut, spec, on_done, kind):
        err = fut.exception()
        if err is not None:
            on_done(exceptions.RayTpuError(f"worker process died: {err}"))
            self._died_abnormally = True
            self._killed.set()
            return
        self._handle_reply(fut.result(), spec, on_done, kind)

    def _handle_reply(self, reply, spec, on_done, kind):
        import pickle
        if reply.get("trace"):
            from ray_tpu.util import tracing
            tracing.ingest(reply["trace"])
        err_blob = reply.get("error")
        if err_blob is not None:
            try:
                err = pickle.loads(err_blob)
            except Exception:
                err = exceptions.RayTpuError("undecodable worker error")
            on_done(err)
            return
        self._store_returns(reply["returns"])
        if kind == "create_actor":
            self.state = WorkerState.ACTOR
            self.actor_id = spec.actor_id
            self._max_concurrency = max(1, spec.max_concurrency)
        on_done(None)

    def _build_payload(self, kind, spec) -> dict:
        from ray_tpu._private.function_manager import _KV_PREFIX
        args = []
        for a in spec.args:
            if a.is_inline:
                args.append(("inline", a.value.to_bytes()))
            else:
                args.append(("ref", a.object_id.binary()))
        fn_key = None
        if spec.function_id is not None:
            fn_key = _KV_PREFIX + spec.function_id.binary()
        return {
            "kind": kind,
            "trace_ctx": getattr(spec, "trace_ctx", None),
            "concurrency_group": getattr(spec, "concurrency_group", ""),
            "concurrency_groups": getattr(spec, "concurrency_groups",
                                          None),
            "function_key": fn_key,
            "function_name": spec.function_name,
            "actor_method_name": spec.actor_method_name,
            "num_returns": spec.num_returns,
            "return_ids": [oid.binary() for oid in spec.return_ids],
            "max_concurrency": spec.max_concurrency,
            "args": args,
            # Context for runtime_context inside the child.
            "task_id": spec.task_id,
            "actor_id": spec.actor_id,
            "resources": spec.resources.to_dict(),
            "placement_group_id": spec.placement_group_id,
            "placement_group_bundle_index":
                spec.placement_group_bundle_index,
            "lifetime_resources":
                spec.lifetime_resources.to_dict()
                if spec.lifetime_resources is not None else None,
            "task_type": spec.task_type,
        }

    def _store_returns(self, returns):
        from ray_tpu._private.serialization import SerializedObject
        core = self.node.core_worker
        for oid_bin, blob in returns:
            oid = ObjectID(oid_bin)
            if blob is None:
                # Worker wrote the value through the shm segment; the
                # host's shm_seal handler already registered the store
                # entry, directory location and memory-store marker.
                continue
            # Owner-correct return storage for BOTH node flavors: the
            # head's CoreWorker seals its own memory store; a spoke's
            # core shim ships small returns to the owner over the wire
            # (put_inline) and directory-registers big ones.
            core.put_serialized_return(
                oid, SerializedObject.from_bytes(blob), self.node)

    def _drain_queue_failing(self, reason: str):
        """Fail every spec currently queued (non-blocking drain)."""
        while True:
            try:
                kind, _spec, on_done = self._queue.get_nowait()
            except queue.Empty:
                return
            if kind != "exit" and on_done is not None:
                on_done(exceptions.RayTpuError(reason))

    def _fail_until_exit(self, reason: str):
        while not self._killed.is_set():
            try:
                kind, _spec, on_done = self._queue.get(timeout=1.0)
            except queue.Empty:
                continue
            if kind == "exit":
                break
            if on_done is not None:
                on_done(exceptions.RayTpuError(reason))
        self._on_exit()

    def _on_exit(self):
        was_actor = self.state == WorkerState.ACTOR
        self.state = WorkerState.DEAD
        host = self._pool._host_service
        if host is not None:
            try:
                if self._died_abnormally:
                    # Crash: the worker OWNED its put objects — seal
                    # OwnerDiedError for borrowers (reference:
                    # OWNER_DIED), then drop whatever it still pinned.
                    host.fail_worker_owned_objects(self.worker_id.hex())
                else:
                    host.release_worker_pins(self.worker_id.hex())
                host.release_worker_shm_pins(self.worker_id.hex())
            except Exception:
                pass
        if self._client is not None:
            try:
                self._client.call("stop", None, timeout=2.0)
            except Exception:
                pass
            self._client.close()
        try:
            self._proc.terminate()
            self._proc.wait(timeout=5.0)
        except Exception:
            try:
                self._proc.kill()
            except Exception:
                pass
        self._pool.on_worker_exit(self)
        if was_actor and self.actor_id is not None:
            self.node.on_actor_worker_exit(self.actor_id, self.worker_id)


# ---- process-wide startup gate (startup-storm throttle) -----------------
# Per-node caps (`maximum_startup_concurrency`) bound ONE pool; a 64-node
# envelope on a shared box is 64 pools spawning at once.  This gate caps
# workers in startup across every pool in the OS process
# (`worker_global_startup_concurrency`); a pop over the cap returns None
# and the dispatch tick retries — exactly the per-node cap's contract,
# applied fleet-wide.  Lock order: WorkerPool._lock may be held when the
# gate is taken, never the reverse.
_global_start_lock = diag_lock("worker_pool._global_start_lock")
_global_starting = 0
_global_throttled = 0


def _acquire_global_start_slots(n: int) -> int:
    """Claim up to ``n`` startup slots; returns how many were granted
    (0 when the gate is saturated).  Shortfall counts as throttling.
    The in-flight counter moves even with the gate disabled, so an
    acquire/release pair stays symmetric across a config flip."""
    global _global_starting, _global_throttled
    if n <= 0:
        return 0
    cap = get_config().worker_global_startup_concurrency
    with _global_start_lock:
        granted = n if cap <= 0 else \
            max(0, min(n, cap - _global_starting))
        _global_starting += granted
        if granted < n:
            _global_throttled += n - granted
    return granted


def _release_global_start_slots(n: int):
    global _global_starting
    if n <= 0:
        return
    with _global_start_lock:
        _global_starting = max(0, _global_starting - n)


def global_startup_in_flight() -> int:
    with _global_start_lock:
        return _global_starting


def global_startup_throttled() -> int:
    """Cumulative pops/prestarts deferred by the process-wide gate."""
    with _global_start_lock:
        return _global_throttled


class WorkerPool:
    def __init__(self, node):
        self._node = node
        # RLock: pop_worker holds it while constructing a ProcessWorker,
        # whose __init__ re-enters via host_service().
        self._lock = diag_rlock("WorkerPool._lock")
        self._idle: List[Worker] = []
        self._leased: Dict[WorkerID, Worker] = {}
        self._actors: Dict[WorkerID, Worker] = {}
        self._all: Dict[WorkerID, Worker] = {}
        cfg = get_config()
        # Total cap is the runaway backstop; maximum_startup_concurrency
        # throttles concurrent SPAWNS, it is not a total cap
        # (worker_pool.h:428 semantics — 10k dedicated actor workers
        # must be reachable).
        self._max_workers = cfg.max_workers_per_node
        self._max_starting = cfg.maximum_startup_concurrency
        self._starting = 0
        self._soft_limit = cfg.num_workers_soft_limit
        self._process_mode = cfg.worker_process_mode == "process"
        self._host_service: Optional[WorkerHostService] = None
        self._log_monitor = None

    def ensure_log_monitor(self):
        """Hold a reference on this process's (singleton) log-file
        tailer, which streams worker log lines into the ``worker_logs``
        pubsub channel.  No-op when no publisher is reachable."""
        with self._lock:
            if self._log_monitor:
                return
            gcs = getattr(getattr(self._node, "cluster", None), "gcs",
                          None)
            publisher = getattr(gcs, "publisher", None)
            if publisher is None:
                return
            from ray_tpu._private import log_monitor as log_monitor_mod
            log_monitor_mod.acquire_local_monitor(publisher)
            self._log_monitor = True

    def host_service(self) -> WorkerHostService:
        with self._lock:
            if self._host_service is None:
                self._host_service = WorkerHostService(self._node)
            return self._host_service

    def _new_worker(self, runtime_env=None):
        if self._process_mode:
            return ProcessWorker(self, self._node, runtime_env=runtime_env)
        return Worker(self, self._node)

    def prestart_workers(self, n: int):
        """Construct outside the lock (same rule as pop_worker: a
        process-mode spawn must not stall concurrent lease traffic)."""
        with self._lock:
            capacity = self._max_workers - len(self._all) - self._starting
            count = max(0, min(n, capacity,
                               self._max_starting - self._starting))
            count = _acquire_global_start_slots(count)
            self._starting += count
        stagger = get_config().worker_startup_stagger_ms / 1000.0
        created = []
        try:
            for i in range(count):
                if i and stagger > 0:
                    # Ramp, don't spike: only this background path
                    # sleeps (prestart runs on a throwaway thread).
                    import time
                    time.sleep(stagger)
                created.append(self._new_worker())
        finally:
            _release_global_start_slots(count)
            with self._lock:
                self._starting -= count
                for w in created:
                    self._all[w.worker_id] = w
                    self._idle.append(w)

    def prestart_for_backlog(self, depth: int, bound: int) -> int:
        """Predictive warm-worker prestart (``PrestartWorkers``,
        worker_pool.h:350): bring idle+starting up to
        ``min(depth, bound)`` workers ahead of ``pop_worker`` so a
        queued burst doesn't pay worker startup one task at a time on
        the dispatch path.  The construction runs on a throwaway daemon
        thread — a process-mode spawn storm must block neither the
        raylet loop nor the submitting thread.  Returns the shortfall
        this call saw (0 = pool already warm enough).  Leased workers
        count as serving the backlog (they cycle back through reuse),
        and the hard worker cap bounds the target — otherwise a
        saturated pool would spawn a futile no-op thread on EVERY
        submit/dispatch edge of a burst."""

        def shortfall() -> int:
            # Callers hold self._lock.
            warm = len(self._idle) + self._starting + len(self._leased)
            room = self._max_workers - len(self._all) - self._starting
            return min(min(depth, bound) - warm, room)

        with self._lock:
            want = shortfall()
        if want <= 0:
            return 0

        def _prestart():
            # Re-check under the pool lock at spawn time: concurrent
            # prestart calls and pop_worker starts shrink the shortfall
            # between the caller's check and this thread running.
            with self._lock:
                n = shortfall()
            if n > 0:
                self.prestart_workers(n)

        threading.Thread(target=_prestart, daemon=True,
                         name="ray_tpu::prestart").start()
        return want

    def pop_worker(self, runtime_env=None) -> Optional[Worker]:
        """Lease an idle worker, starting one if under the cap
        (WorkerPool::PopWorker, worker_pool.h:338).  In process mode
        workers are keyed by runtime-env hash (worker_pool.h:428);
        thread workers are universal (env applied per task)."""
        want_hash = (runtime_env or {}).get("_hash", "") \
            if self._process_mode else ""
        with self._lock:
            kept = []
            found = None
            while self._idle:
                w = self._idle.pop()
                if w.state != WorkerState.IDLE:
                    continue
                if w.runtime_env_hash != want_hash:
                    kept.append(w)
                    continue
                found = w
                break
            self._idle.extend(kept)
            if found is not None:
                found.state = WorkerState.LEASED
                self._leased[found.worker_id] = found
                return found
            total = len(self._all) + self._starting
            if total >= self._max_workers and kept:
                # At the cap with only mismatched-env idle workers:
                # evict one to make room (the reference kills an idle
                # worker rather than starving the new env forever).
                victim = kept[0]
                self._idle.remove(victim)
                self._all.pop(victim.worker_id, None)
                victim.stop()
                total -= 1
            if total >= self._max_workers or \
                    self._starting >= self._max_starting:
                return None      # caller retries on the dispatch tick
            if _acquire_global_start_slots(1) < 1:
                return None      # process-wide storm throttle; retried
            self._starting += 1
        # Construct OUTSIDE the lock: a process-mode spawn materializes
        # the runtime env (KV fetch + unzip) — holding the pool lock for
        # that would stall every concurrent lease/return.
        try:
            w = self._new_worker(runtime_env=runtime_env)
        except BaseException:
            with self._lock:
                self._starting -= 1
            _release_global_start_slots(1)
            raise
        _release_global_start_slots(1)
        with self._lock:
            self._starting -= 1
            self._all[w.worker_id] = w
            w.state = WorkerState.LEASED
            self._leased[w.worker_id] = w
            return w

    def push_worker(self, worker: Worker):
        """Return a leased worker to the idle pool."""
        with self._lock:
            self._leased.pop(worker.worker_id, None)
            if worker.state == WorkerState.DEAD:
                return
            if worker.state == WorkerState.ACTOR:
                self._actors[worker.worker_id] = worker
                return
            worker.state = WorkerState.IDLE
            if len(self._idle) >= self._soft_limit:
                if not self._idle:
                    # soft_limit == 0: keep no idle workers at all.
                    self._all.pop(worker.worker_id, None)
                    worker.stop()
                    return
                # Evict the OLDEST idle worker, not the returning one —
                # the most recently used worker (with its runtime env and
                # warm caches) is the one worth keeping (reference: idle
                # worker killing is LRU, ray_config_def.h:129).
                victim = self._idle.pop(0)
                self._all.pop(victim.worker_id, None)
                victim.stop()
            self._idle.append(worker)

    def promote_to_actor(self, worker: Worker):
        with self._lock:
            self._leased.pop(worker.worker_id, None)
            self._actors[worker.worker_id] = worker

    def on_worker_exit(self, worker: Worker):
        with self._lock:
            self._all.pop(worker.worker_id, None)
            self._leased.pop(worker.worker_id, None)
            self._actors.pop(worker.worker_id, None)
            if worker in self._idle:
                self._idle.remove(worker)

    def worker_for_actor(self, actor_id):
        """The dedicated worker currently running ``actor_id`` (GCS
        restart reconciliation scans surviving raylets with this)."""
        with self._lock:
            # Scan every tracked worker: a dedicated actor worker may sit
            # in _leased (the lease is held by the GCS actor manager and
            # never returned) as well as in _actors.
            for w in self._all.values():
                if w.actor_id == actor_id and w.state == WorkerState.ACTOR:
                    return w
            return None

    def num_idle(self) -> int:
        with self._lock:
            return len(self._idle)

    def num_total(self) -> int:
        with self._lock:
            return len(self._all)

    def shutdown(self):
        with self._lock:
            workers = list(self._all.values())
            host, self._host_service = self._host_service, None
            monitor, self._log_monitor = self._log_monitor, None
        for w in workers:
            w.stop()
        if host is not None:
            host.stop()
        if monitor:
            from ray_tpu._private import log_monitor as log_monitor_mod
            log_monitor_mod.release_local_monitor()
