"""Per-node worker pool.

Parity: reference ``src/ray/raylet/worker_pool.{h,cc}`` — pool of
pre-startable workers, ``PopWorker`` (worker_pool.h:338) /
``PushWorker`` return, ``PrestartWorkers`` (:350), idle soft-cap with
eviction (ray_config_def.h:129), dedicated workers for actors.

TPU-first deviation: workers are *threads in the node's process*, not
subprocesses.  One process per host owns the TPU chips (XLA requires single
ownership), so Python-level parallelism comes from threads — jax compiled
computations release the GIL, and framework logic is IO-bound.  The pool
keeps the reference's lease lifecycle so the scheduler and transport layers
are identical to a multi-process deployment.
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Callable, Dict, List, Optional

from ray_tpu import exceptions
from ray_tpu._private import worker_context
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import WorkerID


class WorkerState:
    IDLE = "IDLE"
    LEASED = "LEASED"
    ACTOR = "ACTOR"
    DEAD = "DEAD"


class Worker:
    """One executor thread; may become dedicated to an actor."""

    def __init__(self, pool: "WorkerPool", node):
        self.worker_id = WorkerID.from_random()
        self.node = node
        self.node_id = node.node_id
        self._pool = pool
        self.state = WorkerState.IDLE
        self._queue: "queue.Queue" = queue.Queue()
        self.actor_id = None
        self.actor_instance = None
        self._actor_threads: List[threading.Thread] = []
        self._max_concurrency = 1
        self._killed = threading.Event()
        self._thread = threading.Thread(
            target=self._main_loop, daemon=True,
            name=f"ray_tpu::worker::{self.worker_id.hex()[:8]}")
        self._thread.start()

    # ---- normal task path ----------------------------------------------
    def push_task(self, spec, on_done: Callable):
        """Execute a normal (or actor-creation) task on this worker
        (CoreWorkerService.PushTask parity)."""
        self._queue.put(("task", spec, on_done))

    def assign_actor(self, creation_spec, on_done: Callable):
        """Run the actor creation task; on success this worker is dedicated
        to the actor until death."""
        self._queue.put(("create_actor", creation_spec, on_done))

    def submit_actor_task(self, spec, on_done: Callable):
        """Ordered actor method execution (sequential_actor_submit_queue
        parity; max_concurrency>1 uses the out-of-order queue)."""
        self._queue.put(("actor_task", spec, on_done))

    def kill_actor(self):
        self._killed.set()
        self._queue.put(("exit", None, None))

    def stop(self):
        self._killed.set()
        self._queue.put(("exit", None, None))

    # ---- main loop ------------------------------------------------------
    def _main_loop(self):
        worker_context.set_context(
            worker_context.ExecutionContext(worker=self, node=self.node))
        from ray_tpu._private import executor as executor_mod
        while not self._killed.is_set():
            try:
                kind, spec, on_done = self._queue.get(timeout=1.0)
            except queue.Empty:
                continue
            if kind == "exit":
                break
            try:
                if kind == "create_actor":
                    self._handle_create_actor(spec, on_done, executor_mod)
                elif kind == "actor_task":
                    self._run_actor_task(spec, on_done, executor_mod)
                else:
                    ok, err = executor_mod.execute_task(
                        spec, self.node, self.node.core_worker)
                    on_done(None if ok else err)
            except Exception as e:  # framework error, not user error
                traceback.print_exc()
                if on_done is not None:
                    on_done(exceptions.RayTpuError(str(e)))
        self._on_exit()

    def _handle_create_actor(self, spec, on_done, executor_mod):
        ok, result = executor_mod.execute_task(
            spec, self.node, self.node.core_worker)
        if not ok:
            on_done(result)
            return
        self.state = WorkerState.ACTOR
        self.actor_id = spec.actor_id
        self.actor_instance = result
        self._max_concurrency = max(1, spec.max_concurrency)
        if self._max_concurrency > 1:
            for i in range(self._max_concurrency - 1):
                t = threading.Thread(target=self._actor_concurrent_loop,
                                     daemon=True,
                                     name=f"{self._thread.name}::cg{i}")
                t.start()
                self._actor_threads.append(t)
        on_done(None)

    def _run_actor_task(self, spec, on_done, executor_mod):
        ok, err = executor_mod.execute_task(
            spec, self.node, self.node.core_worker,
            actor_instance=self.actor_instance)
        on_done(None if ok else err)

    def _actor_concurrent_loop(self):
        worker_context.set_context(
            worker_context.ExecutionContext(worker=self, node=self.node))
        from ray_tpu._private import executor as executor_mod
        while not self._killed.is_set():
            try:
                kind, spec, on_done = self._queue.get(timeout=1.0)
            except queue.Empty:
                continue
            if kind == "exit":
                self._queue.put(("exit", None, None))  # propagate to siblings
                break
            self._run_actor_task(spec, on_done, executor_mod)

    def _on_exit(self):
        was_actor = self.state == WorkerState.ACTOR
        self.state = WorkerState.DEAD
        self._pool.on_worker_exit(self)
        if was_actor and self.actor_id is not None:
            self.node.on_actor_worker_exit(self.actor_id, self.worker_id)


class WorkerPool:
    def __init__(self, node):
        self._node = node
        self._lock = threading.Lock()
        self._idle: List[Worker] = []
        self._leased: Dict[WorkerID, Worker] = {}
        self._actors: Dict[WorkerID, Worker] = {}
        self._all: Dict[WorkerID, Worker] = {}
        cfg = get_config()
        self._max_workers = cfg.maximum_startup_concurrency
        self._soft_limit = cfg.num_workers_soft_limit

    def prestart_workers(self, n: int):
        with self._lock:
            for _ in range(n):
                if len(self._all) >= self._max_workers:
                    break
                w = Worker(self, self._node)
                self._all[w.worker_id] = w
                self._idle.append(w)

    def pop_worker(self) -> Optional[Worker]:
        """Lease an idle worker, starting one if under the cap
        (WorkerPool::PopWorker, worker_pool.h:338)."""
        with self._lock:
            while self._idle:
                w = self._idle.pop()
                if w.state == WorkerState.IDLE:
                    w.state = WorkerState.LEASED
                    self._leased[w.worker_id] = w
                    return w
            if len(self._all) < self._max_workers:
                w = Worker(self, self._node)
                self._all[w.worker_id] = w
                w.state = WorkerState.LEASED
                self._leased[w.worker_id] = w
                return w
            return None

    def push_worker(self, worker: Worker):
        """Return a leased worker to the idle pool."""
        with self._lock:
            self._leased.pop(worker.worker_id, None)
            if worker.state == WorkerState.DEAD:
                return
            if worker.state == WorkerState.ACTOR:
                self._actors[worker.worker_id] = worker
                return
            worker.state = WorkerState.IDLE
            if len(self._idle) >= self._soft_limit:
                worker.stop()
            else:
                self._idle.append(worker)

    def promote_to_actor(self, worker: Worker):
        with self._lock:
            self._leased.pop(worker.worker_id, None)
            self._actors[worker.worker_id] = worker

    def on_worker_exit(self, worker: Worker):
        with self._lock:
            self._all.pop(worker.worker_id, None)
            self._leased.pop(worker.worker_id, None)
            self._actors.pop(worker.worker_id, None)
            if worker in self._idle:
                self._idle.remove(worker)

    def num_idle(self) -> int:
        with self._lock:
            return len(self._idle)

    def num_total(self) -> int:
        with self._lock:
            return len(self._all)

    def shutdown(self):
        with self._lock:
            workers = list(self._all.values())
        for w in workers:
            w.stop()
