"""State API implementation.

Reference: ``python/ray/experimental/state/api.py`` +
``state_aggregator`` — list endpoints with predicate filters and
offset/limit pagination over the GCS tables and the task-event
manager.  Two layers:

* ``*_from_cluster(cluster, ...)`` — used by the head's RPC handlers
  and the dashboard, which hold a cluster object directly;
* ``list_*()`` / ``summarize_tasks()`` — the public driver-side API,
  resolving the global worker's cluster.

Filters are ``(key, op, value)`` tuples with ``op`` in ``{"=", "!="}``;
values compare as strings so callers can filter ids, states and numbers
alike: ``list_tasks(filters=[("state", "=", "FINISHED")])``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

DEFAULT_LIMIT = 100


class StateApiError(RuntimeError):
    pass


def _require_cluster():
    from ray_tpu._private.worker import global_worker_or_none
    w = global_worker_or_none()
    if w is None or not w.connected or w.cluster is None:
        raise StateApiError(
            "ray_tpu.init() has not been called yet (the state API reads "
            "the local cluster's GCS; remote use goes through "
            "`ray-tpu list`)")
    return w.cluster


def _validate_filters(filters: Sequence[Tuple]) -> None:
    for f in filters:
        if len(f) != 3 or f[1] not in ("=", "!="):
            raise StateApiError(
                f"bad filter {f!r}: expected (key, '='|'!=', value)")


def _matches(row: dict, filters: Sequence[Tuple]) -> bool:
    for key, op, value in filters:
        if (op == "=") != (str(row.get(key, "")) == str(value)):
            return False
    return True


def _apply_filters(rows: List[dict],
                   filters: Optional[Sequence[Tuple]]) -> List[dict]:
    if not filters:
        return rows
    _validate_filters(filters)
    return [row for row in rows if _matches(row, filters)]


def _paginate(rows: List[dict], limit: Optional[int],
              offset: int) -> List[dict]:
    if offset:
        rows = rows[offset:]
    if limit is not None:
        rows = rows[:limit]
    return rows


# ---------------------------------------------------------------------------
# cluster-level cores (head RPC handlers + dashboard call these)
# ---------------------------------------------------------------------------

def tasks_from_cluster(cluster, filters=None, limit: Optional[int] = None,
                       offset: int = 0) -> List[dict]:
    from ray_tpu.gcs.task_events import flushed_manager
    mgr = flushed_manager(cluster.gcs)
    if mgr is None:
        return []
    if not filters:
        # Let the manager slice before copying records.
        return mgr.tasks(limit, offset)
    _validate_filters(filters)
    if all(f[0] not in ("duration_s",) for f in filters):
        # Plain record fields: push the predicate down so the manager
        # filters live records before the per-record copies.
        return mgr.tasks(limit, offset,
                         lambda rec: _matches(rec, filters))
    return _paginate(_apply_filters(mgr.tasks(), filters), limit, offset)


def summarize_tasks_from_cluster(cluster) -> dict:
    from ray_tpu.gcs.task_events import flushed_manager
    mgr = flushed_manager(cluster.gcs)
    summary = mgr.summarize() if mgr is not None else {}
    return {
        "summary": summary,
        "total_tasks": mgr.num_tracked() if mgr is not None else 0,
        "dropped_at_source": (mgr.num_dropped_at_source()
                              if mgr is not None else 0),
        "evicted_records": mgr.evicted if mgr is not None else 0,
        # Task-dispatch latency decomposed by lifecycle stage
        # (queue_wait -> dispatch -> startup, "total" = submit->running
        # — the BASELINE.json north-star p99).
        "dispatch_latency": (mgr.latency_summary()
                             if mgr is not None else {}),
        # Causal layer: per-job graph-store accounting (task/finished
        # counts, wall-clock, eviction counters) — the jobs `ray-tpu
        # profile` can answer for.
        "job_graphs": (mgr.job_graphs.summary()
                       if mgr is not None else {}),
    }


def profile_job_from_cluster(cluster, job: Optional[str] = None,
                             top_k: int = 3) -> dict:
    """Critical-path profile of one job (gcs/job_graph.py): walks the
    completed job's task DAG backward from its last-finishing task,
    attributing wall-clock per stage / node / object edge.  ``job`` is
    a job id hex (or unique prefix), or None/"last" for the most
    recently updated job."""
    from ray_tpu.gcs.job_graph import profile_job as _profile
    return _profile(cluster, job, top_k=top_k)


def actors_from_cluster(cluster, filters=None, limit: Optional[int] = None,
                        offset: int = 0) -> List[dict]:
    rows = []
    for aid, info in cluster.gcs.actor_manager.all_actor_info().items():
        row = dict(info)
        row.setdefault("actor_id",
                       aid.hex() if hasattr(aid, "hex") else str(aid))
        rows.append(row)
    return _paginate(_apply_filters(rows, filters), limit, offset)


def objects_from_cluster(cluster, filters=None, limit: Optional[int] = None,
                         offset: int = 0) -> List[dict]:
    """Per-node store entries (sealed state, size, pin count).  Small
    objects living only in owners' in-process memory stores are not
    listed — same scope as the reference, which lists plasma.  KNOWN
    LIMIT: remote node-hosts' stores are proxied over the wire without
    an entry-listing RPC, so only nodes hosted in this process (the
    head and in-process sim nodes) are enumerated."""
    rows = []
    for raylet in cluster.raylets():
        store = getattr(raylet, "object_store", None)
        entries = getattr(store, "_entries", None)
        if entries is None:
            continue
        for oid, entry in list(entries.items()):
            spilled_url = getattr(entry, "spilled_path", None)
            rows.append({
                "object_id": oid.hex() if hasattr(oid, "hex") else str(oid),
                "node_id": raylet.node_id.hex(),
                "size_bytes": getattr(entry, "size", 0),
                "sealed": bool(getattr(entry, "sealed", True)),
                "pin_count": getattr(entry, "pin_count", 0),
                # In-memory data gone + a spill URL = the bytes live on
                # disk only (a restored copy shows spilled=False).
                "spilled": bool(spilled_url
                                and getattr(entry, "data", None) is None),
                "spilled_url": spilled_url or "",
            })
    return _paginate(_apply_filters(rows, filters), limit, offset)


def nodes_from_cluster(cluster, filters=None, limit: Optional[int] = None,
                       offset: int = 0) -> List[dict]:
    """Node liveness rows: ALIVE/SUSPECT/DEAD state, registration
    incarnation, and the fencing evidence (how many messages from stale
    incarnations of this node id the head rejected, by verb)."""
    nm = cluster.gcs.node_manager
    rows = []
    for node_id, info in nm.get_all_node_info().items():
        row = dict(info)
        row["node_id"] = node_id.hex()
        row.setdefault("incarnation", 0)
        row["fenced_rejections"] = nm.fenced_count(node_id)
        row["fenced_by_verb"] = dict(nm.fence_rejections.get(node_id, {}))
        rows.append(row)
    return _paginate(_apply_filters(rows, filters), limit, offset)


# ---------------------------------------------------------------------------
# public driver-side API
# ---------------------------------------------------------------------------

def list_tasks(filters: Optional[Sequence[Tuple]] = None,
               limit: Optional[int] = DEFAULT_LIMIT,
               offset: int = 0) -> List[dict]:
    """Task lifecycle records: latest state, per-state wall-clock
    timestamps (``state_ts``), ordered transition history (``events``),
    attempt counter, node/worker placement and duration."""
    return tasks_from_cluster(_require_cluster(), filters, limit, offset)


def list_actors(filters: Optional[Sequence[Tuple]] = None,
                limit: Optional[int] = DEFAULT_LIMIT,
                offset: int = 0) -> List[dict]:
    return actors_from_cluster(_require_cluster(), filters, limit, offset)


def list_objects(filters: Optional[Sequence[Tuple]] = None,
                 limit: Optional[int] = DEFAULT_LIMIT,
                 offset: int = 0) -> List[dict]:
    return objects_from_cluster(_require_cluster(), filters, limit, offset)


def list_nodes(filters: Optional[Sequence[Tuple]] = None,
               limit: Optional[int] = DEFAULT_LIMIT,
               offset: int = 0) -> List[dict]:
    return nodes_from_cluster(_require_cluster(), filters, limit, offset)


def summarize_tasks() -> dict:
    """Per-function rollup: counts by state, mean/total duration, plus
    the pipeline's loss accounting (drop/eviction counters)."""
    return summarize_tasks_from_cluster(_require_cluster())


def profile_job(job: Optional[str] = None, top_k: int = 3) -> dict:
    """Driver-side critical-path profile (``ray-tpu profile`` parity):
    stage/node/edge attribution along the job's dependency chain."""
    return profile_job_from_cluster(_require_cluster(), job, top_k)
