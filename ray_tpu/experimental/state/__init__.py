"""State API (reference ``ray.experimental.state.api``): list and
summarize cluster entities — tasks (from the task-event pipeline,
``gcs/task_events.py``), actors, objects and nodes — with filters and
pagination, plus the causal job profiler (``profile_job``,
``gcs/job_graph.py``).  The CLI (``ray-tpu list/summary/profile``) and
the dashboard's ``/api/tasks`` + ``/api/profile`` routes are thin
wrappers over this module."""

from ray_tpu.experimental.state.api import (  # noqa: F401
    StateApiError, list_actors, list_nodes, list_objects, list_tasks,
    profile_job, summarize_tasks)

__all__ = ["list_tasks", "list_actors", "list_objects", "list_nodes",
           "summarize_tasks", "profile_job", "StateApiError"]
