"""State API (reference ``ray.experimental.state.api``): list and
summarize cluster entities — tasks (from the task-event pipeline,
``gcs/task_events.py``), actors, objects and nodes — with filters and
pagination.  The CLI (``ray-tpu list/summary``) and the dashboard's
``/api/tasks`` route are thin wrappers over this module."""

from ray_tpu.experimental.state.api import (  # noqa: F401
    StateApiError, list_actors, list_nodes, list_objects, list_tasks,
    summarize_tasks)

__all__ = ["list_tasks", "list_actors", "list_objects", "list_nodes",
           "summarize_tasks", "StateApiError"]
