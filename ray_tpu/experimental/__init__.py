"""Experimental public surfaces (reference ``ray.experimental``)."""
