"""Bundle -> node packing for placement groups.

Parity: reference ``src/ray/gcs/gcs_server/gcs_resource_scheduler.{h,cc}``
(``GcsResourceScheduler::Schedule`` with PACK/SPREAD/STRICT_PACK/
STRICT_SPREAD, gcs_resource_scheduler.h:29-40,108; best-fit via
``LeastResourceScorer`` :74 — after-allocation leftover minimized).

This is the shared solve surface: the numpy implementation below is the
oracle, and ``ray_tpu.scheduler.jax_backend`` exposes the same contract for
batched solves on TPU (SURVEY.md §3.4: one kernel signature serves the
raylet tick, GCS PG packing, and the autoscaler bin-pack).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ray_tpu.scheduler.resources import (
    ClusterResourceView, NodeResources, ResourceRequest)


def _least_resource_score(avail: Dict[str, int], demand: Dict[str, int]) -> float:
    """LeastResourceScorer (gcs_resource_scheduler.h:74): prefer the node
    that, after allocation, has the least leftover of the demanded
    resources (best fit).  Returns -inf if infeasible."""
    score = 0.0
    for name, amount in demand.items():
        have = avail.get(name, 0)
        if have < amount:
            return float("-inf")
        score += 1.0 - (have - amount) / max(have, 1)
    return score / max(len(demand), 1)


def pack_bundles(view: ClusterResourceView,
                 bundles: Sequence[ResourceRequest],
                 strategy: str,
                 exclude_nodes: Optional[Set] = None) -> Optional[List]:
    """Solve bundle->node placement; returns node id per bundle or None.

    All-or-nothing: placement is simulated on a copy of the availability
    maps so a partial fit never leaks into the live view (the actual
    reservation happens via the 2PC prepare/commit against raylets).
    """
    node_ids = view.node_ids()
    exclude_nodes = exclude_nodes or set()
    node_ids = [n for n in node_ids if n not in exclude_nodes]
    if not node_ids:
        return None
    sim: Dict = {}
    for nid in node_ids:
        res = view.node_resources(nid)
        if res is None:
            continue
        sim[nid] = dict(res.available)

    if strategy == "STRICT_PACK":
        total: Dict[str, int] = {}
        for b in bundles:
            for k, v in b.quantized().items():
                total[k] = total.get(k, 0) + v
        best, best_score = None, float("-inf")
        for nid in node_ids:
            s = _least_resource_score(sim[nid], total)
            if s > best_score:
                best, best_score = nid, s
        if best is None or best_score == float("-inf"):
            return None
        return [best] * len(bundles)

    # Sort large bundles first (first-fit-decreasing flavor), keep the
    # original index to un-permute the answer.
    order = sorted(range(len(bundles)),
                   key=lambda i: -sum(bundles[i].quantized().values()))
    placement: List = [None] * len(bundles)
    used_nodes: Set = set()

    for i in order:
        demand = bundles[i].quantized()
        best, best_score = None, float("-inf")
        for nid in node_ids:
            if strategy == "STRICT_SPREAD" and nid in used_nodes:
                continue
            s = _least_resource_score(sim[nid], demand)
            if s == float("-inf"):
                continue
            # PACK prefers already-used nodes; SPREAD prefers fresh nodes.
            if strategy == "PACK" and nid in used_nodes:
                s += 10.0
            elif strategy == "SPREAD" and nid in used_nodes:
                s -= 10.0
            if s > best_score:
                best, best_score = nid, s
        if best is None:
            return None
        placement[i] = best
        used_nodes.add(best)
        for k, v in demand.items():
            sim[best][k] = sim[best].get(k, 0) - v
    return placement


def bundle_resource_names(pg_id, bundle_index: int,
                          resources: ResourceRequest) -> Dict[str, float]:
    """Formatted placement-group resources added to a node on commit.

    Reference scheme (``bundle_spec.h``): for each resource R in the bundle,
    the node gains ``R_group_{pg_id}`` (wildcard) and
    ``R_group_{index}_{pg_id}`` (indexed); tasks using the PG consume those
    instead of the base resources.
    """
    out: Dict[str, float] = {}
    hexid = pg_id.hex()
    for name, amount in resources.to_dict().items():
        out[f"{name}_group_{hexid}"] = amount
        out[f"{name}_group_{bundle_index}_{hexid}"] = amount
    # The indexed "bundle" marker resource (bundle_spec.h): lets zero-cpu
    # tasks target a bundle and lets pg.ready() probe placement.
    out[f"bundle_group_{hexid}"] = 1000
    out[f"bundle_group_{bundle_index}_{hexid}"] = 1000
    return out


def rewrite_resources_for_bundle(resources: Dict[str, float], pg_id,
                                 bundle_index: int) -> Dict[str, float]:
    """Rewrite a task's resource demand to the PG-formatted resources."""
    hexid = pg_id.hex()
    out: Dict[str, float] = {}
    for name, amount in resources.items():
        if bundle_index >= 0:
            out[f"{name}_group_{bundle_index}_{hexid}"] = amount
        else:
            out[f"{name}_group_{hexid}"] = amount
    # Always demand a sliver of the bundle marker so even zero-resource
    # tasks wait for (and land on) the bundle's node.
    if bundle_index >= 0:
        out.setdefault(f"bundle_group_{bundle_index}_{hexid}", 0.001)
    else:
        out.setdefault(f"bundle_group_{hexid}", 0.001)
    return out
