"""Bundle -> node packing for placement groups.

Parity: reference ``src/ray/gcs/gcs_server/gcs_resource_scheduler.{h,cc}``
(``GcsResourceScheduler::Schedule`` with PACK/SPREAD/STRICT_PACK/
STRICT_SPREAD, gcs_resource_scheduler.h:29-40,108; best-fit via
``LeastResourceScorer`` :74 — after-allocation leftover minimized).

This is the shared solve surface: ``pack_bundles`` routes through the
TPU bundle kernel (``jax_backend._jit_pack_bundles`` — strategy
semantics as cost terms/masks in ONE device scan per group) whenever
the cluster is big enough for the dispatch to pay for itself
(``pg_kernel_backend``/``pg_kernel_min_nodes``), and keeps the numpy
greedy below as the small-cluster/CPU fallback AND the validation
oracle: kernel output is re-validated against the exact quantized
vectors host-side, and any failure — kernel error, invalid assignment,
kernel-infeasible — falls back to the greedy solve, so the two paths
can never silently diverge on feasibility (SURVEY.md §3.4: one kernel
signature serves the raylet tick, GCS PG packing, and the autoscaler
bin-pack).
"""

from __future__ import annotations

import importlib.util
import logging
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ray_tpu._private.config import get_config
from ray_tpu.scheduler.resources import (
    ClusterResourceView, NodeResources, ResourceRequest)

logger = logging.getLogger(__name__)

_JAX_OK = importlib.util.find_spec("jax") is not None

# Solve-surface telemetry (exported by GcsPlacementGroupManager's
# collector): how often PG packing rode the kernel vs fell back.
kernel_stats = {"kernel_placements": 0, "kernel_misses": 0,
                "kernel_errors": 0, "greedy_placements": 0}


def _kernel_enabled(num_nodes: int) -> bool:
    cfg = get_config()
    mode = cfg.pg_kernel_backend
    if mode == "off" or not _JAX_OK:
        return False
    if mode == "force":
        return True
    return num_nodes >= cfg.pg_kernel_min_nodes


def _least_resource_score(avail: Dict[str, int], demand: Dict[str, int]) -> float:
    """LeastResourceScorer (gcs_resource_scheduler.h:74): prefer the node
    that, after allocation, has the least leftover of the demanded
    resources (best fit).  Returns -inf if infeasible."""
    score = 0.0
    for name, amount in demand.items():
        have = avail.get(name, 0)
        if have < amount:
            return float("-inf")
        score += 1.0 - (have - amount) / max(have, 1)
    return score / max(len(demand), 1)


def pack_bundles(view: ClusterResourceView,
                 bundles: Sequence[ResourceRequest],
                 strategy: str,
                 exclude_nodes: Optional[Set] = None) -> Optional[List]:
    """Solve bundle->node placement; returns node id per bundle or None.

    All-or-nothing: placement is simulated on a copy of the availability
    maps so a partial fit never leaks into the live view (the actual
    reservation happens via the 2PC prepare/commit against raylets).

    Routing: the TPU bundle kernel solves the group in one device call
    when enabled (``_kernel_enabled``); its output is validated against
    the exact quantized vectors, and a miss/error of any kind falls
    through to the numpy greedy solve below — the kernel can only ADD
    placements, never lose one the greedy would have found.
    """
    if _kernel_enabled(view.num_nodes()):
        try:
            assignment = pack_bundles_kernel(view, bundles, strategy,
                                             exclude_nodes)
        except Exception:
            kernel_stats["kernel_errors"] += 1
            logger.exception("PG bundle kernel failed; greedy fallback")
            assignment = None
        if assignment is not None:
            kernel_stats["kernel_placements"] += 1
            return assignment
        kernel_stats["kernel_misses"] += 1
    result = _pack_bundles_greedy(view, bundles, strategy, exclude_nodes)
    if result is not None:
        kernel_stats["greedy_placements"] += 1
    return result


def validate_assignment(view: ClusterResourceView,
                        bundles: Sequence[ResourceRequest],
                        assignment: List, strategy: str,
                        exclude_nodes: Set) -> bool:
    """Exact host-side check of a proposed bundle->node assignment
    against the quantized per-node vectors (the raylet-authoritative
    validation the task tick applies to kernel output): sequential
    feasibility, exclusion, and the hard strategy constraints."""
    sim: Dict = {}
    if strategy == "STRICT_PACK" and len(set(assignment)) > 1:
        return False
    if strategy == "STRICT_SPREAD" and \
            len(set(assignment)) != len(assignment):
        return False
    for nid, bundle in zip(assignment, bundles):
        if nid in exclude_nodes:
            return False
        if nid not in sim:
            res = view.node_resources(nid)
            if res is None:
                return False
            sim[nid] = dict(res.available)
        have = sim[nid]
        for k, v in bundle.quantized().items():
            if have.get(k, 0) < v:
                return False
            have[k] = have[k] - v
    return True


def pack_bundles_kernel(view: ClusterResourceView,
                        bundles: Sequence[ResourceRequest],
                        strategy: str,
                        exclude_nodes: Optional[Set] = None
                        ) -> Optional[List]:
    """One-device-call bundle->node solve (``_jit_pack_bundles``).

    Host side does exactly what the greedy does around its loop: sort
    large bundles first (FFD), collapse STRICT_PACK into one composite
    row, then validate the kernel's assignment against the exact
    quantized vectors.  Returns None (caller falls back to greedy) on
    any miss."""
    from ray_tpu.scheduler.jax_backend import BatchSolver
    exclude_nodes = exclude_nodes or set()
    if not bundles or any(not b.quantized() for b in bundles):
        return None                  # empty bundles: greedy's edge case
    if view.num_nodes() == 0:
        return None
    if strategy == "STRICT_PACK":
        combined: Dict[str, float] = {}
        for b in bundles:
            for k, v in b.to_dict().items():
                combined[k] = combined.get(k, 0.0) + v
        reqs = [ResourceRequest(combined)]
        order = [0]
    else:
        order = sorted(range(len(bundles)),
                       key=lambda i: -sum(bundles[i].quantized().values()))
        reqs = [bundles[i] for i in order]
    # demand_matrix first (it may create columns), ONE snapshot after.
    demand = view.demand_matrix(reqs)
    node_ids, total, avail, columns = view.snapshot()
    if not node_ids:
        return None
    if demand.shape[1] < total.shape[1]:
        demand = np.pad(demand,
                        ((0, 0), (0, total.shape[1] - demand.shape[1])))
    excluded = np.array([nid in exclude_nodes for nid in node_ids],
                        dtype=bool)
    idx, ok = BatchSolver().solve_bundles(avail, total, demand, strategy,
                                          excluded)
    if not ok.all():
        return None
    if strategy == "STRICT_PACK":
        node = node_ids[int(idx[0])] if 0 <= int(idx[0]) < len(node_ids) \
            else None
        if node is None:
            return None
        assignment: List = [node] * len(bundles)
    else:
        assignment = [None] * len(bundles)
        for j, i in enumerate(order):
            n = int(idx[j])
            if not 0 <= n < len(node_ids):
                return None
            assignment[i] = node_ids[n]
    if not validate_assignment(view, bundles, assignment, strategy,
                               exclude_nodes):
        return None
    return assignment


def _pack_bundles_greedy(view: ClusterResourceView,
                         bundles: Sequence[ResourceRequest],
                         strategy: str,
                         exclude_nodes: Optional[Set] = None
                         ) -> Optional[List]:
    """Reference-parity numpy greedy (LeastResourceScorer best-fit) —
    the small-cluster fallback and the kernel's validation oracle."""
    node_ids = view.node_ids()
    exclude_nodes = exclude_nodes or set()
    node_ids = [n for n in node_ids if n not in exclude_nodes]
    if not node_ids:
        return None
    sim: Dict = {}
    for nid in node_ids:
        res = view.node_resources(nid)
        if res is None:
            continue
        sim[nid] = dict(res.available)

    if strategy == "STRICT_PACK":
        total: Dict[str, int] = {}
        for b in bundles:
            for k, v in b.quantized().items():
                total[k] = total.get(k, 0) + v
        best, best_score = None, float("-inf")
        for nid in node_ids:
            s = _least_resource_score(sim[nid], total)
            if s > best_score:
                best, best_score = nid, s
        if best is None or best_score == float("-inf"):
            return None
        return [best] * len(bundles)

    # Sort large bundles first (first-fit-decreasing flavor), keep the
    # original index to un-permute the answer.
    order = sorted(range(len(bundles)),
                   key=lambda i: -sum(bundles[i].quantized().values()))
    placement: List = [None] * len(bundles)
    used_nodes: Set = set()

    for i in order:
        demand = bundles[i].quantized()
        best, best_score = None, float("-inf")
        for nid in node_ids:
            if strategy == "STRICT_SPREAD" and nid in used_nodes:
                continue
            s = _least_resource_score(sim[nid], demand)
            if s == float("-inf"):
                continue
            # PACK prefers already-used nodes; SPREAD prefers fresh nodes.
            if strategy == "PACK" and nid in used_nodes:
                s += 10.0
            elif strategy == "SPREAD" and nid in used_nodes:
                s -= 10.0
            if s > best_score:
                best, best_score = nid, s
        if best is None:
            return None
        placement[i] = best
        used_nodes.add(best)
        for k, v in demand.items():
            sim[best][k] = sim[best].get(k, 0) - v
    return placement


def bundle_resource_names(pg_id, bundle_index: int,
                          resources: ResourceRequest) -> Dict[str, float]:
    """Formatted placement-group resources added to a node on commit.

    Reference scheme (``bundle_spec.h``): for each resource R in the bundle,
    the node gains ``R_group_{pg_id}`` (wildcard) and
    ``R_group_{index}_{pg_id}`` (indexed); tasks using the PG consume those
    instead of the base resources.
    """
    out: Dict[str, float] = {}
    hexid = pg_id.hex()
    for name, amount in resources.to_dict().items():
        out[f"{name}_group_{hexid}"] = amount
        out[f"{name}_group_{bundle_index}_{hexid}"] = amount
    # The indexed "bundle" marker resource (bundle_spec.h): lets zero-cpu
    # tasks target a bundle and lets pg.ready() probe placement.
    out[f"bundle_group_{hexid}"] = 1000
    out[f"bundle_group_{bundle_index}_{hexid}"] = 1000
    return out


def rewrite_resources_for_bundle(resources: Dict[str, float], pg_id,
                                 bundle_index: int) -> Dict[str, float]:
    """Rewrite a task's resource demand to the PG-formatted resources."""
    hexid = pg_id.hex()
    out: Dict[str, float] = {}
    for name, amount in resources.items():
        if bundle_index >= 0:
            out[f"{name}_group_{bundle_index}_{hexid}"] = amount
        else:
            out[f"{name}_group_{hexid}"] = amount
    # Always demand a sliver of the bundle marker so even zero-resource
    # tasks wait for (and land on) the bundle's node.
    if bundle_index >= 0:
        out.setdefault(f"bundle_group_{bundle_index}_{hexid}", 0.001)
    else:
        out.setdefault(f"bundle_group_{hexid}", 0.001)
    return out
