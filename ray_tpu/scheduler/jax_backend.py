"""TPU-resident batched scheduling kernel — the north star.

Replaces the reference's single-task greedy loop
(``HybridSchedulingPolicy::Schedule`` iterated per task,
``cluster_task_manager.cc:67-123``) with one batched solve per tick:

    demand[C, R] x counts[C] x avail[N, R] -> alloc[C, N]

where C is the number of *scheduling classes* (tasks deduped by interned
resource shape, ``task_spec.h:297`` — 1M pending tasks collapse to ~100s of
rows, SURVEY.md §3.4) and N the number of nodes.

Node ordering is **bucketized**: instead of a total order by exact score
(a 10k-element sort per class — 256 sequential sorts per tick), nodes are
binned into 19 priority buckets and filled in (bucket, node-id) order:

    bucket 0      — below the spread threshold (hybrid policy truncation,
                    ``hybrid_scheduling_policy.cc:100-133``)
    buckets 1-16  — critical-resource utilization quantized to 1/16
    bucket 17     — accelerator nodes avoided by non-accelerator classes
                    (``scheduler_avoid_gpu_nodes`` parity)
    bucket 18     — empty/dead/padded nodes

This mirrors the reference's real semantics (it picks among a top-k
candidate set, not a strict total order) and makes the per-class step
sort-free: prefix capacities come from a two-level blocked cumsum
(groups of 128 nodes), all dense vector ops that XLA maps onto the TPU's
VPU.  The fill is still exact water-filling — capacity-consistent within
the tick because the scan over classes carries the availability matrix.

Two more levels of TPU-residency (used by bench.py):
  * ``prepare_device`` uploads avail/total/masks once; per-tick calls ship
    only the [C] counts vector (the queue snapshot), not the [N, R] world.
  * ``solve_stream`` runs K ticks in ONE device program (scan over ticks),
    returning a fixed-size sparse encoding of each tick's assignment plus
    on-device validation flags — amortizing dispatch latency, which
    dominates when the chip is remote (PCIe on a real v4-8 host, RPC over
    the dev tunnel).

Two solvers behind one contract:
  * ``waterfill`` (default, exact): deterministic bucketized fill —
    golden-tested against a numpy oracle with identical semantics.
  * ``sinkhorn``: cost = utilization score masked by feasibility; a
    masked-softmax transport plan iterated to respect capacities, then
    rounded with a capacity-aware fill using the plan as node ordering.
    Load-balances like SPREAD while respecting capacities.

The raylet stays authoritative: kernel output is validated against the
exact fixed-point vectors before commit and falls back to the native
policy (``ClusterTaskManager._schedule_batched``) — dirty/stale views are
tolerated exactly like spillback.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private.config import get_config
from ray_tpu.scheduler.resources import ACCELERATOR_COLUMNS

_BIG = 1e9
_NUM_BUCKETS = 19
_UTIL_LEVELS = 16
_GROUP = 128  # node-axis block for the two-level prefix (lane width)


def _pad_to(x: np.ndarray, shape: Tuple[int, ...]) -> np.ndarray:
    pads = [(0, s - d) for s, d in zip(shape, x.shape)]
    return np.pad(x, pads)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------------------------------------------------------------------------
# Shared per-class fill (device).
# ---------------------------------------------------------------------------

def _bucket_fill_step(av, total, d, cnt, is_accel, accel_node, empty,
                      spread_threshold):
    """One class's water-fill against the running availability.

    Layout is TPU-native: av/total are [R, N] (resources on the 8-wide
    sublane axis, nodes on the 128-wide lane axis — N is padded to a
    multiple of 128 so every op is tile-aligned) and bucket tensors are
    [B, N] for the same reason.  Returns (new_av[R,N], take[N]).

    All f32; prefix sums stay exact for integer capacities while the
    running prefix is < 2^24, beyond which the prefix already dwarfs any
    class count so take clamps to 0.
    """
    import jax.numpy as jnp

    eps = 1e-6
    n_pad = av.shape[1]
    demanded = d > 0                                       # [R]
    any_demand = jnp.any(demanded)
    # How many tasks of this class fit on each node.
    ratios = jnp.where(demanded[:, None],
                       av / jnp.maximum(d[:, None], eps), _BIG)
    cap = jnp.floor(jnp.min(ratios, axis=0) + eps)         # [N]
    cap = jnp.clip(cap, 0.0, cnt)
    # Hybrid score: current critical-resource utilization over the
    # demanded resources (hybrid_scheduling_policy.cc:100-133).
    util = jnp.where(total > 0, (total - av) / jnp.maximum(total, eps), 0.0)
    score_demanded = jnp.max(
        jnp.where(demanded[:, None], util, -_BIG), axis=0)
    score_overall = jnp.max(util, axis=0)
    score = jnp.where(any_demand, score_demanded, score_overall)  # [N]
    # Bucketize: below threshold -> 0; else utilization quantized.
    scale = _UTIL_LEVELS / jnp.maximum(1.0 - spread_threshold, eps)
    lvl = jnp.clip(
        jnp.floor((score - spread_threshold) * scale) + 1.0,
        1.0, float(_UTIL_LEVELS))
    bucket = jnp.where(score < spread_threshold, 0.0, lvl)
    bucket = jnp.where(jnp.logical_and(accel_node, ~is_accel),
                       float(_UTIL_LEVELS + 1), bucket)
    bucket = jnp.where(empty, float(_NUM_BUCKETS - 1), bucket)
    bucket = bucket.astype(jnp.int32)
    # Prefix capacity in (bucket, node-id) order — sort-free, [B, N].
    onehot = (bucket[None, :] ==
              jnp.arange(_NUM_BUCKETS, dtype=jnp.int32)[:, None])
    cap_oh = jnp.where(onehot, cap[None, :], 0.0)          # [B, N]
    g = cap_oh.reshape(_NUM_BUCKETS, n_pad // _GROUP, _GROUP)
    gsum = jnp.sum(g, axis=2)                              # [B, G]
    gprefix = jnp.cumsum(gsum, axis=1) - gsum              # excl. over groups
    within = jnp.cumsum(g, axis=2) - g                     # excl. in group
    prefix_bn = (within + gprefix[:, :, None]).reshape(
        _NUM_BUCKETS, n_pad)
    btotal = jnp.sum(gsum, axis=1)                         # [B]
    bprefix = jnp.cumsum(btotal) - btotal                  # excl. over buckets
    # Select each node's own-bucket entry (masked sum avoids a gather).
    prefix = jnp.sum(jnp.where(onehot, prefix_bn + bprefix[:, None], 0.0),
                     axis=0)
    take = jnp.clip(cnt - prefix, 0.0, cap)
    av = av - take[None, :] * d[:, None]
    return av, take


# ---------------------------------------------------------------------------
# Device kernels (jit-compiled once per padded shape).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=16)
def _jit_waterfill(c_pad: int, n_pad: int, r_pad: int):
    import jax
    import jax.numpy as jnp

    def solve(avail, total, demand, counts, accel_node, accel_class,
              spread_threshold):
        # avail/total: [N, R]; demand: [C, R]; counts: [C].  Transposed
        # once to the TPU-native [R, N] layout (see _bucket_fill_step).
        av_t, total_t = avail.T, total.T
        empty = jnp.max(total_t, axis=0) <= 0

        def body(av, inputs):
            d, cnt, is_accel = inputs
            return _bucket_fill_step(av, total_t, d, cnt, is_accel,
                                     accel_node, empty, spread_threshold)

        final_avail, allocs = jax.lax.scan(
            body, av_t, (demand, counts, accel_class))
        return allocs, final_avail.T

    return jax.jit(solve)


@functools.lru_cache(maxsize=8)
def _jit_waterfill_stream(c_pad: int, n_pad: int, r_pad: int,
                          ticks: int, nnz_max: int):
    """K scheduler ticks in one device program.

    Closed loop, device-resident queue state: the per-class pending-task
    vector is the scan carry — each tick's queue is
    ``pending + arrivals_k`` (arrivals are the exogenous input stream),
    the solve places what fits, and the remainder carries to the next
    tick: ``pending' = pending + arrivals_k - placed_per_class``.  The
    availability snapshot resets each tick (steady state: a tick's
    placements drain within the tick).  Output is ONE packed f32 array
    [K, 2*nnz_max + 3] — per tick: sparse indices (exact in f32 while
    C_pad*N_pad < 2^24), sparse values, then (placed, ok, nnz) — so the
    host needs a single fetch per program.
    """
    import jax
    import jax.numpy as jnp

    assert c_pad * n_pad < (1 << 24), "sparse idx must stay exact in f32"

    def solve(avail0, total, demand, pending0, arrivals, accel_node,
              accel_class, spread_threshold):
        av0_t, total_t = avail0.T, total.T                 # [R, N]
        empty = jnp.max(total_t, axis=0) <= 0
        flat_n = c_pad * n_pad

        def one_tick(pending, arrivals_k):
            counts_k = pending + arrivals_k
            def body(av, inputs):
                d, cnt, is_accel = inputs
                return _bucket_fill_step(av, total_t, d, cnt, is_accel,
                                         accel_node, empty, spread_threshold)

            _, allocs = jax.lax.scan(
                body, av0_t, (demand, counts_k, accel_class), unroll=8)
            # On-device validation: capacity + per-class count bounds.
            usage = jnp.einsum("cn,cr->rn", allocs, demand)
            ok_cap = jnp.all(usage <= av0_t + 1e-2)
            placed_c = jnp.sum(allocs, axis=1)             # [C]
            ok_cnt = jnp.all(placed_c <= counts_k + 0.5)
            placed = jnp.sum(placed_c)
            pending_next = jnp.maximum(counts_k - placed_c, 0.0)
            # Fixed-size sparse encoding (class*N + node, value), via the
            # gather dual of stream compaction: binary-search the inclusive
            # rank cumsum for the j-th nonzero (TPU scatter at this size is
            # ~2.5x slower than searchsorted+gather).
            flat = allocs.reshape(flat_n)
            ranks = jnp.cumsum((flat > 0).astype(jnp.int32))
            nnz = ranks[-1]
            pos = jnp.searchsorted(
                ranks, jnp.arange(1, nnz_max + 1, dtype=jnp.int32))
            live = jnp.arange(nnz_max) < nnz
            posc = jnp.minimum(pos, flat_n - 1)
            idx = jnp.where(live, posc, flat_n)
            vals = jnp.where(live, flat[posc], 0.0)
            ok = ok_cap & ok_cnt & (nnz <= nnz_max)
            packed = jnp.concatenate([
                idx.astype(jnp.float32), vals,
                jnp.stack([placed, ok.astype(jnp.float32),
                           nnz.astype(jnp.float32)])])
            return pending_next, packed

        _, out = jax.lax.scan(one_tick, pending0, arrivals)
        return out

    return jax.jit(solve)


@functools.lru_cache(maxsize=16)
def _jit_sinkhorn(c_pad: int, n_pad: int, r_pad: int, iters: int):
    import jax
    import jax.numpy as jnp

    def solve(avail, total, demand, counts, accel_node, accel_class,
              spread_threshold, tau):
        eps = 1e-6
        # Feasibility + initial per-(class,node) capacity in task units.
        demanded = demand > 0                              # [C, R]
        ratios = jnp.where(demanded[:, None, :],
                           avail[None, :, :] /
                           jnp.maximum(demand[:, None, :], eps), _BIG)
        cap = jnp.floor(jnp.min(ratios, axis=2) + eps)     # [C, N]
        cap = jnp.minimum(cap, counts[:, None])
        feasible = cap > 0
        # Cost: utilization + accel penalty (same shape as waterfill).
        util = jnp.where(total > 0, (total - avail) /
                         jnp.maximum(total, eps), 0.0)     # [N, R]
        score = jnp.einsum("nr,cr->cn",
                           util, demanded.astype(jnp.float32))
        score = score / jnp.maximum(
            jnp.sum(demanded, axis=1, dtype=jnp.float32)[:, None], 1.0)
        score = jnp.where(score < spread_threshold, 0.0, score)
        score = score + (accel_node[None, :] &
                         ~accel_class[:, None]) * 1.0
        logits = jnp.where(feasible, -score / tau, -_BIG)
        # Masked-softmax transport plan, row-targets = counts.
        plan = jax.nn.softmax(logits, axis=1) * counts[:, None]  # [C, N]
        # Column capacity in "task slots" is class-dependent; approximate
        # the shared multi-resource constraint per resource: scale columns
        # so per-resource usage fits availability.
        def sinkhorn_iter(plan, _):
            usage = jnp.einsum("cn,cr->nr", plan, demand)      # [N, R]
            factor = jnp.min(
                jnp.where(usage > eps,
                          jnp.clip(avail / jnp.maximum(usage, eps), 0.0, 1.0),
                          1.0),
                axis=1)                                        # [N]
            plan = plan * factor[None, :]
            # Re-normalize rows back toward counts (never exceeding them).
            row = jnp.sum(plan, axis=1, keepdims=True)
            plan = plan * jnp.where(row > eps,
                                    jnp.minimum(counts[:, None] /
                                                jnp.maximum(row, eps),
                                                _BIG),
                                    0.0)
            plan = jnp.minimum(plan, cap)
            return plan, None

        plan, _ = jax.lax.scan(sinkhorn_iter, plan, None, length=iters)

        # Round: fill nodes per class in plan-descending order, re-checking
        # capacity against the running availability (exactness restored).
        def body(av, inputs):
            d, cnt, p = inputs
            demanded_r = d > 0
            ratios = jnp.where(demanded_r[None, :],
                               av / jnp.maximum(d[None, :], eps), _BIG)
            capn = jnp.floor(jnp.min(ratios, axis=1) + eps)
            capn = jnp.clip(capn, 0.0, cnt)
            order = jnp.argsort(-p, stable=True)
            cap_sorted = capn[order]
            prefix = jnp.cumsum(cap_sorted) - cap_sorted
            take_sorted = jnp.clip(cnt - prefix, 0.0, cap_sorted)
            alloc = jnp.zeros((n_pad,), jnp.float32).at[order].set(take_sorted)
            av = av - alloc[:, None] * d[None, :]
            return av, alloc

        final_avail, allocs = jax.lax.scan(body, avail,
                                           (demand, counts, plan))
        return allocs, final_avail

    return jax.jit(solve)


# ---------------------------------------------------------------------------
# numpy oracle (golden reference for tests).
# ---------------------------------------------------------------------------

def bucket_oracle(score: np.ndarray, accel_avoid: np.ndarray,
                  empty: np.ndarray, spread_threshold: float) -> np.ndarray:
    """Quantize scores into fill-priority buckets (same spec as device)."""
    thr = np.float32(spread_threshold)
    scale = np.float32(_UTIL_LEVELS) / max(np.float32(1.0) - thr,
                                           np.float32(1e-6))
    lvl = np.clip(np.floor((score - thr) * scale) + 1.0, 1.0, _UTIL_LEVELS)
    bucket = np.where(score < thr, 0.0, lvl)
    bucket = np.where(accel_avoid, _UTIL_LEVELS + 1, bucket)
    bucket = np.where(empty, _NUM_BUCKETS - 1, bucket)
    return bucket.astype(np.int32)


def waterfill_oracle(avail: np.ndarray, total: np.ndarray,
                     demand: np.ndarray, counts: np.ndarray,
                     accel_node: np.ndarray, accel_class: np.ndarray,
                     spread_threshold: float) -> np.ndarray:
    """Pure-numpy reference of the bucketized waterfill (same semantics).

    Float32 throughout so score/bucket boundaries match the device kernel
    bit-for-bit."""
    avail = avail.astype(np.float32).copy()
    total = total.astype(np.float32)
    C, R = demand.shape
    N = avail.shape[0]
    alloc = np.zeros((C, N), dtype=np.int64)
    eps = np.float32(1e-6)
    empty = total.max(axis=1) <= 0
    for c in range(C):
        d = demand[c].astype(np.float32)
        cnt = int(counts[c])
        if cnt == 0:
            continue
        demanded = d > 0
        if demanded.any():
            ratios = np.where(demanded[None, :],
                              avail / np.maximum(d[None, :], eps), _BIG)
            cap = np.floor(ratios.min(axis=1) + eps)
        else:
            cap = np.full(N, _BIG, dtype=np.float32)
        cap = np.clip(cap, 0, cnt).astype(np.int64)
        util = np.where(total > 0, (total - avail) / np.maximum(total, eps),
                        np.float32(0.0)).astype(np.float32)
        if demanded.any():
            score = np.where(demanded[None, :], util,
                             np.float32(-_BIG)).max(axis=1)
        else:
            score = util.max(axis=1)
        accel_avoid = accel_node & (not accel_class[c])
        bucket = bucket_oracle(score.astype(np.float32), accel_avoid, empty,
                               spread_threshold)
        order = np.argsort(bucket, kind="stable")
        remaining = cnt
        for n in order:
            if remaining <= 0:
                break
            take = min(remaining, int(cap[n]))
            if take > 0:
                alloc[c, n] = take
                avail[n] -= take * d
                remaining -= take
    return alloc


# ---------------------------------------------------------------------------
# Host-side driver.
# ---------------------------------------------------------------------------

class BatchSolver:
    """Groups pending specs by scheduling class, runs the device solve,
    expands the allocation back to per-task node targets."""

    def __init__(self, mode: Optional[str] = None, sinkhorn_iters: int = 8):
        self.mode = mode or "waterfill"
        self.sinkhorn_iters = sinkhorn_iters
        self._device_state = None  # set by prepare_device

    # -- raw matrix interface (used by bench + autoscaler) ---------------
    def solve_matrices(self, avail: np.ndarray, total: np.ndarray,
                       demand: np.ndarray, counts: np.ndarray,
                       accel_node: Optional[np.ndarray] = None,
                       accel_class: Optional[np.ndarray] = None,
                       spread_threshold: Optional[float] = None):
        """Returns alloc[C,N] int64 for one tick."""
        import jax
        C, R = demand.shape
        N = avail.shape[0]
        c_pad, n_pad, r_pad = self._pads(C, N, R)
        accel_node, accel_class, spread_threshold = self._defaults(
            N, C, accel_node, accel_class, spread_threshold)
        args = (
            _pad_to(avail.astype(np.float32), (n_pad, r_pad)),
            _pad_to(total.astype(np.float32), (n_pad, r_pad)),
            _pad_to(demand.astype(np.float32), (c_pad, r_pad)),
            _pad_to(counts.astype(np.float32), (c_pad,)),
            _pad_to(accel_node.astype(bool), (n_pad,)),
            _pad_to(accel_class.astype(bool), (c_pad,)),
        )
        if self.mode == "sinkhorn":
            fn = _jit_sinkhorn(c_pad, n_pad, r_pad, self.sinkhorn_iters)
            allocs, _ = fn(*args, np.float32(spread_threshold),
                           np.float32(0.1))
        else:
            fn = _jit_waterfill(c_pad, n_pad, r_pad)
            allocs, _ = fn(*args, np.float32(spread_threshold))
        allocs = np.asarray(jax.device_get(allocs))[:C, :N]
        return np.rint(allocs).astype(np.int64)

    # -- device-resident tick-stream interface (used by bench) -----------
    def prepare_device(self, avail: np.ndarray, total: np.ndarray,
                       demand: np.ndarray,
                       accel_node: Optional[np.ndarray] = None,
                       accel_class: Optional[np.ndarray] = None,
                       spread_threshold: Optional[float] = None) -> None:
        """Upload the cluster world-state once; subsequent solve_stream
        calls ship only per-tick queue counts."""
        import jax
        C, R = demand.shape
        N = avail.shape[0]
        c_pad, n_pad, r_pad = self._pads(C, N, R)
        accel_node, accel_class, spread_threshold = self._defaults(
            N, C, accel_node, accel_class, spread_threshold)
        dev = {
            "avail": jax.device_put(
                _pad_to(avail.astype(np.float32), (n_pad, r_pad))),
            "total": jax.device_put(
                _pad_to(total.astype(np.float32), (n_pad, r_pad))),
            "demand": jax.device_put(
                _pad_to(demand.astype(np.float32), (c_pad, r_pad))),
            "accel_node": jax.device_put(
                _pad_to(accel_node.astype(bool), (n_pad,))),
            "accel_class": jax.device_put(
                _pad_to(accel_class.astype(bool), (c_pad,))),
            "thr": np.float32(spread_threshold),
            "shape": (C, N, R), "pads": (c_pad, n_pad, r_pad),
        }
        jax.block_until_ready([dev["avail"], dev["total"], dev["demand"]])
        self._device_state = dev

    def solve_stream(self, arrivals: np.ndarray,
                     pending0: Optional[np.ndarray] = None,
                     nnz_max: int = 32768) -> Dict[str, np.ndarray]:
        """Run K closed-loop ticks on device.

        arrivals is [K, C]: the exogenous per-tick task arrivals per
        scheduling class.  The pending queue is device-resident scan
        state: each tick solves ``pending + arrivals_k`` and carries the
        unplaced remainder forward.  Returns sparse assignments +
        validation per tick: ``idx`` [K, nnz_max] in the PADDED flat
        space (class*N_pad + node; decode with ``expand_sparse``, which
        knows this solver's padding), ``vals`` [K, nnz_max],
        ``placed`` [K], ``ok`` [K], ``nnz`` [K]."""
        import jax
        assert self._device_state is not None, "call prepare_device first"
        dev = self._device_state
        C, N, R = dev["shape"]
        c_pad, n_pad, r_pad = dev["pads"]
        K = arrivals.shape[0]
        if pending0 is None:
            pending0 = np.zeros(C, dtype=np.float32)
        fn = _jit_waterfill_stream(c_pad, n_pad, r_pad, K, nnz_max)
        arr = _pad_to(arrivals.astype(np.float32), (K, c_pad))
        pen = _pad_to(pending0.astype(np.float32), (c_pad,))
        packed = np.asarray(fn(
            dev["avail"], dev["total"], dev["demand"], pen, arr,
            dev["accel_node"], dev["accel_class"], dev["thr"]))
        return {
            "idx": np.rint(packed[:, :nnz_max]).astype(np.int64),
            "vals": packed[:, nnz_max:2 * nnz_max],
            "placed": packed[:, 2 * nnz_max],
            "ok": packed[:, 2 * nnz_max + 1] > 0.5,
            "nnz": np.rint(packed[:, 2 * nnz_max + 2]).astype(np.int64),
        }

    def expand_sparse(self, idx: np.ndarray, vals: np.ndarray
                      ) -> np.ndarray:
        """Decode one tick's sparse assignment to dense alloc[C, N]."""
        assert self._device_state is not None
        C, N, R = self._device_state["shape"]
        c_pad, n_pad, _ = self._device_state["pads"]
        alloc = np.zeros((c_pad, n_pad), dtype=np.int64)
        live = idx < c_pad * n_pad
        alloc.reshape(-1)[idx[live]] = np.rint(vals[live]).astype(np.int64)
        return alloc[:C, :N]

    @staticmethod
    def _pads(C: int, N: int, R: int) -> Tuple[int, int, int]:
        return (_round_up(max(C, 1), 8), _round_up(max(N, 8), _GROUP),
                _round_up(max(R, 1), 8))

    @staticmethod
    def _defaults(N, C, accel_node, accel_class, spread_threshold):
        if accel_node is None:
            accel_node = np.zeros(N, dtype=bool)
        if accel_class is None:
            accel_class = np.zeros(C, dtype=bool)
        if spread_threshold is None:
            spread_threshold = get_config().scheduler_spread_threshold
        return accel_node, accel_class, spread_threshold

    # -- spec interface (used by ClusterTaskManager) ---------------------
    def assign(self, view, specs: Sequence) -> List:
        """Per-spec node targets (None = infeasible/unassigned)."""
        from ray_tpu.scheduler.policy import SchedulingType
        node_ids, total, avail, columns = view.snapshot()
        if not node_ids:
            return [None] * len(specs)
        # Group hybrid-class specs; everything else single-task fallback.
        groups: Dict[int, List[int]] = {}
        fallback: List[int] = []
        for i, spec in enumerate(specs):
            if spec.scheduling_options.scheduling_type is SchedulingType.HYBRID:
                groups.setdefault(spec.scheduling_class, []).append(i)
            else:
                fallback.append(i)
        targets: List = [None] * len(specs)
        if groups:
            classes = list(groups.keys())
            reqs = [specs[groups[c][0]].resources for c in classes]
            demand = view.demand_matrix(reqs)
            # demand_matrix may have added columns; re-snapshot widths.
            node_ids, total, avail, columns = view.snapshot()
            if demand.shape[1] < total.shape[1]:
                demand = _pad_to(demand, (demand.shape[0], total.shape[1]))
            counts = np.array([len(groups[c]) for c in classes])
            accel_node = np.zeros(len(node_ids), dtype=bool)
            for col in ACCELERATOR_COLUMNS:
                if col < total.shape[1]:
                    accel_node |= total[:, col] > 0
            accel_class = np.array([r.uses_accelerator() for r in reqs])
            alloc = self.solve_matrices(avail, total, demand, counts,
                                        accel_node, accel_class)
            for ci, cls in enumerate(classes):
                members = groups[cls]
                k = 0
                for n in range(len(node_ids)):
                    for _ in range(int(alloc[ci, n])):
                        if k < len(members):
                            targets[members[k]] = node_ids[n]
                            k += 1
        if fallback:
            from ray_tpu.scheduler import policy as policy_mod
            for i in fallback:
                targets[i] = policy_mod.schedule(
                    view, specs[i].resources, specs[i].scheduling_options,
                    local_node_id=None)
        return targets
