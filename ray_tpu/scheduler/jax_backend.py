"""TPU-resident batched scheduling kernel — the north star.

Replaces the reference's single-task greedy loop
(``HybridSchedulingPolicy::Schedule`` iterated per task,
``cluster_task_manager.cc:67-123``) with one batched solve per tick:

    demand[C, R] x counts[C] x avail[N, R] -> alloc[C, N]

where C is the number of *scheduling classes* (tasks deduped by interned
resource shape, ``task_spec.h:297`` — 1M pending tasks collapse to ~100s of
rows, SURVEY.md §3.4) and N the number of nodes.  Everything is dense
float32 linear algebra + one sort per class, so XLA maps it onto the TPU's
vector units; the scan over classes carries the availability matrix so
assignment is capacity-consistent *within* the tick.

Two solvers behind one contract:
  * ``waterfill`` (default, exact): per class, capacity per node =
    floor(min_r avail/demand); nodes ordered by the hybrid policy's
    critical-resource-utilization score (threshold-truncated, accelerator
    nodes penalized for non-accelerator classes); tasks fill nodes in that
    order.  Deterministic — golden-tested against a numpy oracle.
  * ``sinkhorn``: cost = utilization score masked by feasibility; a
    masked-softmax transport plan row-normalized to class counts and
    column-scaled to node capacities for K iterations, then rounded with
    the same capacity-aware fill using the plan as the node ordering.
    Load-balances like SPREAD while respecting capacities.

The raylet stays authoritative: kernel output is validated against the
exact fixed-point vectors before commit and falls back to the native
policy (``ClusterTaskManager._schedule_batched``) — dirty/stale views are
tolerated exactly like spillback.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private.config import get_config
from ray_tpu.scheduler.resources import ACCELERATOR_COLUMNS

_BIG = 1e9


def _pad_to(x: np.ndarray, shape: Tuple[int, ...]) -> np.ndarray:
    pads = [(0, s - d) for s, d in zip(shape, x.shape)]
    return np.pad(x, pads)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------------------------------------------------------------------------
# Device kernels (jit-compiled once per padded shape).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=16)
def _jit_waterfill(c_pad: int, n_pad: int, r_pad: int):
    import jax
    import jax.numpy as jnp

    def solve(avail, total, demand, counts, accel_node, accel_class,
              spread_threshold):
        # avail/total: [N, R]; demand: [C, R]; counts: [C]
        eps = 1e-6

        def body(av, inputs):
            d, cnt, is_accel = inputs
            demanded = d > 0
            any_demand = jnp.any(demanded)
            # How many tasks of this class fit on each node.
            ratios = jnp.where(demanded[None, :],
                               av / jnp.maximum(d[None, :], eps), _BIG)
            cap = jnp.floor(jnp.min(ratios, axis=1) + eps)
            cap = jnp.clip(cap, 0.0, cnt)
            # Hybrid score: current critical-resource utilization over the
            # demanded resources, truncated below the spread threshold
            # (hybrid_scheduling_policy.cc:100-133).
            util = jnp.where(total > 0, (total - av) / jnp.maximum(total, eps),
                             0.0)
            score_demanded = jnp.max(
                jnp.where(demanded[None, :], util, -_BIG), axis=1)
            score_overall = jnp.max(util, axis=1)
            score = jnp.where(any_demand, score_demanded, score_overall)
            score = jnp.where(score < spread_threshold, 0.0, score)
            # Keep accelerator nodes for accelerator work
            # (scheduler_avoid_gpu_nodes parity).
            score = score + jnp.where(jnp.logical_and(accel_node,
                                                      ~is_accel), 1.0, 0.0)
            # Dead/padded nodes (total==0 everywhere) must sort last.
            empty = jnp.max(total, axis=1) <= 0
            score = jnp.where(empty, _BIG, score)
            # Fill nodes in score order (stable -> node-id tie-break).
            order = jnp.argsort(score, stable=True)
            cap_sorted = cap[order]
            prefix = jnp.cumsum(cap_sorted) - cap_sorted
            take_sorted = jnp.clip(cnt - prefix, 0.0, cap_sorted)
            alloc = jnp.zeros((n_pad,), jnp.float32).at[order].set(take_sorted)
            av = av - alloc[:, None] * d[None, :]
            return av, alloc

        final_avail, allocs = jax.lax.scan(
            body, avail, (demand, counts, accel_class))
        return allocs, final_avail

    return jax.jit(solve, static_argnames=())


@functools.lru_cache(maxsize=16)
def _jit_sinkhorn(c_pad: int, n_pad: int, r_pad: int, iters: int):
    import jax
    import jax.numpy as jnp

    def solve(avail, total, demand, counts, accel_node, accel_class,
              spread_threshold, tau):
        eps = 1e-6
        # Feasibility + initial per-(class,node) capacity in task units.
        demanded = demand > 0                              # [C, R]
        ratios = jnp.where(demanded[:, None, :],
                           avail[None, :, :] /
                           jnp.maximum(demand[:, None, :], eps), _BIG)
        cap = jnp.floor(jnp.min(ratios, axis=2) + eps)     # [C, N]
        cap = jnp.minimum(cap, counts[:, None])
        feasible = cap > 0
        # Cost: utilization + accel penalty (same shape as waterfill).
        util = jnp.where(total > 0, (total - avail) /
                         jnp.maximum(total, eps), 0.0)     # [N, R]
        score = jnp.einsum("nr,cr->cn",
                           util, demanded.astype(jnp.float32))
        score = score / jnp.maximum(
            jnp.sum(demanded, axis=1, dtype=jnp.float32)[:, None], 1.0)
        score = jnp.where(score < spread_threshold, 0.0, score)
        score = score + (accel_node[None, :] &
                         ~accel_class[:, None]) * 1.0
        logits = jnp.where(feasible, -score / tau, -_BIG)
        # Masked-softmax transport plan, row-targets = counts.
        plan = jax.nn.softmax(logits, axis=1) * counts[:, None]  # [C, N]
        # Column capacity in "task slots" is class-dependent; approximate
        # the shared multi-resource constraint per resource: scale columns
        # so per-resource usage fits availability.
        def sinkhorn_iter(plan, _):
            usage = jnp.einsum("cn,cr->nr", plan, demand)      # [N, R]
            factor = jnp.min(
                jnp.where(usage > eps,
                          jnp.clip(avail / jnp.maximum(usage, eps), 0.0, 1.0),
                          1.0),
                axis=1)                                        # [N]
            plan = plan * factor[None, :]
            # Re-normalize rows back toward counts (never exceeding them).
            row = jnp.sum(plan, axis=1, keepdims=True)
            plan = plan * jnp.where(row > eps,
                                    jnp.minimum(counts[:, None] /
                                                jnp.maximum(row, eps),
                                                _BIG),
                                    0.0)
            plan = jnp.minimum(plan, cap)
            return plan, None

        plan, _ = jax.lax.scan(sinkhorn_iter, plan, None, length=iters)

        # Round: fill nodes per class in plan-descending order, re-checking
        # capacity against the running availability (exactness restored).
        def body(av, inputs):
            d, cnt, p = inputs
            demanded_r = d > 0
            ratios = jnp.where(demanded_r[None, :],
                               av / jnp.maximum(d[None, :], eps), _BIG)
            capn = jnp.floor(jnp.min(ratios, axis=1) + eps)
            capn = jnp.clip(capn, 0.0, cnt)
            order = jnp.argsort(-p, stable=True)
            cap_sorted = capn[order]
            prefix = jnp.cumsum(cap_sorted) - cap_sorted
            take_sorted = jnp.clip(cnt - prefix, 0.0, cap_sorted)
            alloc = jnp.zeros((n_pad,), jnp.float32).at[order].set(take_sorted)
            av = av - alloc[:, None] * d[None, :]
            return av, alloc

        final_avail, allocs = jax.lax.scan(body, avail,
                                           (demand, counts, plan))
        return allocs, final_avail

    return jax.jit(solve)


# ---------------------------------------------------------------------------
# numpy oracle (golden reference for tests).
# ---------------------------------------------------------------------------

def waterfill_oracle(avail: np.ndarray, total: np.ndarray,
                     demand: np.ndarray, counts: np.ndarray,
                     accel_node: np.ndarray, accel_class: np.ndarray,
                     spread_threshold: float) -> np.ndarray:
    """Pure-numpy reference of the waterfill solve (same semantics)."""
    avail = avail.astype(np.float64).copy()
    total = total.astype(np.float64)
    C, R = demand.shape
    N = avail.shape[0]
    alloc = np.zeros((C, N), dtype=np.int64)
    eps = 1e-6
    for c in range(C):
        d = demand[c].astype(np.float64)
        cnt = int(counts[c])
        if cnt == 0:
            continue
        demanded = d > 0
        if demanded.any():
            ratios = np.where(demanded[None, :],
                              avail / np.maximum(d[None, :], eps), _BIG)
            cap = np.floor(ratios.min(axis=1) + eps)
        else:
            cap = np.full(N, _BIG)
        cap = np.clip(cap, 0, cnt).astype(np.int64)
        util = np.where(total > 0, (total - avail) / np.maximum(total, eps),
                        0.0)
        if demanded.any():
            score = np.where(demanded[None, :], util, -_BIG).max(axis=1)
        else:
            score = util.max(axis=1)
        score = np.where(score < spread_threshold, 0.0, score)
        score = score + np.where(accel_node & (not accel_class[c]), 1.0, 0.0)
        score = np.where(total.max(axis=1) <= 0, _BIG, score)
        order = np.argsort(score, kind="stable")
        remaining = cnt
        for n in order:
            if remaining <= 0:
                break
            take = min(remaining, int(cap[n]))
            if take > 0:
                alloc[c, n] = take
                avail[n] -= take * d
                remaining -= take
    return alloc


# ---------------------------------------------------------------------------
# Host-side driver.
# ---------------------------------------------------------------------------

class BatchSolver:
    """Groups pending specs by scheduling class, runs the device solve,
    expands the allocation back to per-task node targets."""

    def __init__(self, mode: Optional[str] = None, sinkhorn_iters: int = 8):
        self.mode = mode or "waterfill"
        self.sinkhorn_iters = sinkhorn_iters

    # -- raw matrix interface (used by bench + autoscaler) ---------------
    def solve_matrices(self, avail: np.ndarray, total: np.ndarray,
                       demand: np.ndarray, counts: np.ndarray,
                       accel_node: Optional[np.ndarray] = None,
                       accel_class: Optional[np.ndarray] = None,
                       spread_threshold: Optional[float] = None):
        """Returns (alloc[C,N] int64, device_seconds)."""
        import jax
        C, R = demand.shape
        N = avail.shape[0]
        c_pad, n_pad, r_pad = _round_up(max(C, 1), 8), \
            _round_up(max(N, 8), 128), _round_up(max(R, 1), 8)
        if accel_node is None:
            accel_node = np.zeros(N, dtype=bool)
        if accel_class is None:
            accel_class = np.zeros(C, dtype=bool)
        if spread_threshold is None:
            spread_threshold = get_config().scheduler_spread_threshold
        args = (
            _pad_to(avail.astype(np.float32), (n_pad, r_pad)),
            _pad_to(total.astype(np.float32), (n_pad, r_pad)),
            _pad_to(demand.astype(np.float32), (c_pad, r_pad)),
            _pad_to(counts.astype(np.float32), (c_pad,)),
            _pad_to(accel_node.astype(bool), (n_pad,)),
            _pad_to(accel_class.astype(bool), (c_pad,)),
        )
        if self.mode == "sinkhorn":
            fn = _jit_sinkhorn(c_pad, n_pad, r_pad, self.sinkhorn_iters)
            allocs, _ = fn(*args, np.float32(spread_threshold),
                           np.float32(0.1))
        else:
            fn = _jit_waterfill(c_pad, n_pad, r_pad)
            allocs, _ = fn(*args, np.float32(spread_threshold))
        allocs = np.asarray(jax.device_get(allocs))[:C, :N]
        return np.rint(allocs).astype(np.int64)

    # -- spec interface (used by ClusterTaskManager) ---------------------
    def assign(self, view, specs: Sequence) -> List:
        """Per-spec node targets (None = infeasible/unassigned)."""
        from ray_tpu.scheduler.policy import SchedulingType
        node_ids, total, avail, columns = view.snapshot()
        if not node_ids:
            return [None] * len(specs)
        # Group hybrid-class specs; everything else single-task fallback.
        groups: Dict[int, List[int]] = {}
        fallback: List[int] = []
        for i, spec in enumerate(specs):
            if spec.scheduling_options.scheduling_type is SchedulingType.HYBRID:
                groups.setdefault(spec.scheduling_class, []).append(i)
            else:
                fallback.append(i)
        targets: List = [None] * len(specs)
        if groups:
            classes = list(groups.keys())
            reqs = [specs[groups[c][0]].resources for c in classes]
            demand = view.demand_matrix(reqs)
            # demand_matrix may have added columns; re-snapshot widths.
            node_ids, total, avail, columns = view.snapshot()
            if demand.shape[1] < total.shape[1]:
                demand = _pad_to(demand, (demand.shape[0], total.shape[1]))
            counts = np.array([len(groups[c]) for c in classes])
            accel_node = np.zeros(len(node_ids), dtype=bool)
            for col in ACCELERATOR_COLUMNS:
                if col < total.shape[1]:
                    accel_node |= total[:, col] > 0
            accel_class = np.array([r.uses_accelerator() for r in reqs])
            alloc = self.solve_matrices(avail, total, demand, counts,
                                        accel_node, accel_class)
            for ci, cls in enumerate(classes):
                members = groups[cls]
                k = 0
                for n in range(len(node_ids)):
                    for _ in range(int(alloc[ci, n])):
                        if k < len(members):
                            targets[members[k]] = node_ids[n]
                            k += 1
        if fallback:
            from ray_tpu.scheduler import policy as policy_mod
            for i in fallback:
                targets[i] = policy_mod.schedule(
                    view, specs[i].resources, specs[i].scheduling_options,
                    local_node_id=None)
        return targets
