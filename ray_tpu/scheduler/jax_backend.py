"""TPU-resident batched scheduling kernel — the north star.

Replaces the reference's single-task greedy loop
(``HybridSchedulingPolicy::Schedule`` iterated per task,
``cluster_task_manager.cc:67-123``) with one batched solve per tick:

    demand[C, R] x counts[C] x avail[N, R] -> alloc[C, N]

where C is the number of *scheduling classes* (tasks deduped by interned
resource shape, ``task_spec.h:297`` — 1M pending tasks collapse to ~100s of
rows, SURVEY.md §3.4) and N the number of nodes.

Node ordering is **bucketized**: instead of a total order by exact score
(a 10k-element sort per class — 256 sequential sorts per tick), nodes are
binned into 19 priority buckets and filled in (bucket, rotated-node-id)
order:

    bucket 0      — below the spread threshold (hybrid policy truncation,
                    ``hybrid_scheduling_policy.cc:100-133``)
    buckets 1-16  — critical-resource utilization quantized to 1/16
    bucket 17     — accelerator nodes avoided by non-accelerator classes
                    (``scheduler_avoid_gpu_nodes`` parity)
    bucket 18     — empty/dead/padded nodes

Within a bucket the fill order is node id **rotated by a per-class
stride** (class c starts at node ``(c * 977) % N_pad``), so concurrent
classes don't all pile onto low-id nodes.  NOTE this is a documented
divergence from the reference's strict min-utilization pick
(``hybrid_scheduling_policy.cc:114-133``): within one 1/16 utilization
bucket the reference would still order by exact score; here ties at
bucket granularity fill round-robin-by-class instead — oracle-matched and
validated against exact vectors before commit wherever it is consumed
(``ClusterTaskManager._schedule_batched``, autoscaler bin-pack).

This mirrors the reference's real semantics (it picks among a top-k
candidate set, not a strict total order) and makes the per-class step
sort-free: prefix capacities come from a two-level blocked cumsum
(groups of 128 nodes), all dense vector ops that XLA maps onto the TPU's
VPU.  The fill is still exact water-filling — capacity-consistent within
the tick because the scan over classes carries the availability matrix.

Three levels of TPU-residency:
  * ``prepare_device`` uploads avail/total/masks once; per-tick calls ship
    only the [C] counts vector (the queue snapshot), not the [N, R] world.
  * ``solve_stream`` runs K ticks in ONE device program (scan over ticks)
    with FULLY closed-loop world state: the pending queue, the evolving
    availability matrix AND the inflight-work matrix are all scan carries
    — placements subtract capacity, a geometric completion process
    (per-class rate ``rho``) releases it back.  Returns a fixed-size
    sparse encoding of each tick's assignment plus on-device validation
    flags — amortizing dispatch latency, which dominates when the chip
    is remote (PCIe on a real v4-8 host, RPC over the dev tunnel).
  * ``DeviceRuntimeSolver`` is the **runtime dispatch path**: a raylet's
    ``ClusterTaskManager`` keeps the cluster world state device-resident
    between scheduling ticks, shipping only dirty-row deltas (nodes whose
    availability changed) down and one sparse assignment back per tick.

Two solvers behind one contract:
  * ``waterfill`` (default, exact): deterministic bucketized fill —
    golden-tested against a numpy oracle with identical semantics.
  * ``sinkhorn``: cost = utilization score masked by feasibility; a
    masked-softmax transport plan iterated to respect capacities, then
    rounded with a capacity-aware fill using the plan as node ordering.
    Load-balances like SPREAD while respecting capacities.

The raylet stays authoritative: kernel output is validated against the
exact fixed-point vectors before commit and falls back to the native
policy (``ClusterTaskManager._schedule_greedy``) — dirty/stale views are
tolerated exactly like spillback.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private.config import get_config
from ray_tpu.scheduler.resources import accelerator_node_mask

_BIG = 1e9
_UTIL_LEVELS = 16
# Cost pre-buckets BELOW the utilization mapping: the per-(class, node)
# cost term moves a node by floor(cost * scale + 1/2) buckets, and a
# negative (preferred) cost needs somewhere to land even when the whole
# fleet sits in the flat below-threshold bucket — without these the
# cost would saturate against bucket 0 and locality/heterogeneity
# preferences would be invisible on an idle cluster.
_COST_BUCKETS = 16
# 16 pre-buckets + [flat below-threshold, 16 util levels, accel-avoid,
# empty] = 35.
_NUM_BUCKETS = _COST_BUCKETS + _UTIL_LEVELS + 3
_GROUP = 128  # node-axis block for the two-level prefix (lane width)
_ROT_STRIDE = 977  # per-class rotation stride (prime, coprime with N_pad)

# Node labels feeding the heterogeneity cost term (Gavel-style
# effective-rate scaling, PAPERS.md 2008.09213): a float throughput
# multiplier per node, with an optional accelerator-class override so
# the rate matrix is genuinely per-class x per-node.  Unlabeled nodes
# rate 1.0; all-equal rates produce a zero cost term (no behavior
# change).
NODE_THROUGHPUT_LABEL = "ray_tpu.throughput"
NODE_ACCEL_THROUGHPUT_LABEL = "ray_tpu.accel_throughput"


def _label_rate(labels: Dict, key: str, default: float = 1.0) -> float:
    try:
        return max(float(labels.get(key, default)), 1e-3)
    except (TypeError, ValueError):
        return default


def _pad_to(x: np.ndarray, shape: Tuple[int, ...]) -> np.ndarray:
    pads = [(0, s - d) for s, d in zip(shape, x.shape)]
    return np.pad(x, pads)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------------------------------------------------------------------------
# Shared per-class fill (device).
# ---------------------------------------------------------------------------

def _bucket_fill_step(av, total, d, cnt, is_accel, shift, cost_row, invert,
                      accel_node, empty, spread_threshold):
    """One class's water-fill against the running availability.

    Layout is TPU-native: av/total are [R, N] (resources on the 8-wide
    sublane axis, nodes on the 128-wide lane axis — N is padded to a
    multiple of 128 so every op is tile-aligned) and bucket tensors are
    [B, N] for the same reason.  ``shift`` rotates the within-bucket fill
    order (see module docstring).  Returns (new_av[R,N], take[N]).

    Cost-matrix extension (the unified-scheduler surface): ``cost_row``
    [N] is this class's per-node cost added to the utilization score
    BEFORE bucketization — negative cost pulls a node into an earlier
    fill bucket.  In utilization units: 1/16 per bucket.  Carries the
    heterogeneity term (Gavel-style effective-rate: slower nodes cost
    more), the arg-locality bonus (nodes holding a class's argument
    bytes cost less) and the PG-PACK used-node bonus.  ``invert`` flips
    the utilization ordering (score := 1 - util): most-utilized
    feasible nodes fill first — bin-packing/PACK mode for the
    autoscaler's node-count solve (with zero per-class shifts the
    within-bucket order is plain node id, i.e. first-fit).

    All f32; prefix sums stay exact for integer capacities while the
    running prefix is < 2^24, beyond which the prefix already dwarfs any
    class count so take clamps to 0.
    """
    import jax
    import jax.numpy as jnp

    eps = 1e-6
    n_pad = av.shape[1]
    demanded = d > 0                                       # [R]
    any_demand = jnp.any(demanded)
    # How many tasks of this class fit on each node.
    ratios = jnp.where(demanded[:, None],
                       av / jnp.maximum(d[:, None], eps), _BIG)
    cap = jnp.floor(jnp.min(ratios, axis=0) + eps)         # [N]
    cap = jnp.clip(cap, 0.0, cnt)
    # Hybrid score: current critical-resource utilization over the
    # demanded resources (hybrid_scheduling_policy.cc:100-133).
    util = jnp.where(total > 0, (total - av) / jnp.maximum(total, eps), 0.0)
    score_demanded = jnp.max(
        jnp.where(demanded[:, None], util, -_BIG), axis=0)
    score_overall = jnp.max(util, axis=0)
    score = jnp.where(any_demand, score_demanded, score_overall)  # [N]
    score = jnp.where(invert > 0, 1.0 - score, score)
    # Bucketize: below threshold -> flat pack zone; else utilization
    # quantized — then offset by the cost term in BUCKET units (with
    # 16 pre-buckets below the pack zone so preferences resolve even
    # when the whole fleet ties at bucket 0).  cost == 0 shifts
    # uniformly by _COST_BUCKETS: identical fill order to the cost-free
    # kernel.
    scale = _UTIL_LEVELS / jnp.maximum(1.0 - spread_threshold, eps)
    lvl = jnp.clip(
        jnp.floor((score - spread_threshold) * scale) + 1.0,
        1.0, float(_UTIL_LEVELS))
    b_util = jnp.where(score < spread_threshold, 0.0, lvl)
    cost_b = jnp.floor(cost_row * scale + 0.5)
    bucket = jnp.clip(b_util + float(_COST_BUCKETS) + cost_b,
                      0.0, float(_COST_BUCKETS + _UTIL_LEVELS))
    bucket = jnp.where(jnp.logical_and(accel_node, ~is_accel),
                       float(_COST_BUCKETS + _UTIL_LEVELS + 1), bucket)
    bucket = jnp.where(empty, float(_NUM_BUCKETS - 1), bucket)
    bucket = bucket.astype(jnp.int32)
    # Prefix capacity in (bucket, rotated node-id) order — sort-free,
    # [B, N], and roll-free: instead of materializing the rolled tensor
    # (two full [B, N] memory passes), compute the NATURAL-order
    # per-bucket exclusive prefix P and decompose the rotation
    # analytically.  With Q[b] = P[b, shift] (capacity in bucket b
    # before the rotation start) and S[b] the bucket total, a node n's
    # within-bucket prefix in rotated order is
    #     n >= shift:  P[b, n] - Q[b]          (nodes [shift, n))
    #     n <  shift:  S[b] - Q[b] + P[b, n]   (wrap: [shift, N) + [0, n))
    onehot = (bucket[None, :] ==
              jnp.arange(_NUM_BUCKETS, dtype=jnp.int32)[:, None])
    cap_oh = jnp.where(onehot, cap[None, :], 0.0)          # [B, N]
    g = cap_oh.reshape(_NUM_BUCKETS, n_pad // _GROUP, _GROUP)
    gsum = jnp.sum(g, axis=2)                              # [B, G]
    gprefix = jnp.cumsum(gsum, axis=1) - gsum              # excl. over groups
    # Within-group exclusive prefix as ONE strictly-lower-triangular
    # matmul on the MXU (f32-exact below 2^24) instead of log2(128)
    # VPU shift passes over the [B, N] tensor.
    tri = jnp.triu(jnp.ones((_GROUP, _GROUP), jnp.float32), k=1)
    within = jax.lax.dot_general(
        g, tri, (((2,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)               # [B, G, GROUP]
    p_nat = (within + gprefix[:, :, None]).reshape(_NUM_BUCKETS, n_pad)
    btotal = jnp.sum(gsum, axis=1)                         # [B]  (= S)
    q_at_shift = jax.lax.dynamic_slice_in_dim(
        p_nat, shift, 1, axis=1)[:, 0]                     # [B]  (= Q)
    bprefix = jnp.cumsum(btotal) - btotal                  # excl. over buckets
    wrap = jnp.where(jnp.arange(n_pad) < shift,
                     btotal[:, None], 0.0)                 # [B, N]
    prefix_bn = p_nat - q_at_shift[:, None] + wrap + bprefix[:, None]
    # Select each node's own-bucket entry (masked sum avoids a gather).
    prefix = jnp.sum(jnp.where(onehot, prefix_bn, 0.0), axis=0)
    take = jnp.clip(cnt - prefix, 0.0, cap)
    av = av - take[None, :] * d[:, None]
    return av, take


def _class_shifts(c_pad: int, n_pad: int):
    """Per-class within-bucket rotation offsets (device)."""
    import jax.numpy as jnp
    return (jnp.arange(c_pad, dtype=jnp.int32) * _ROT_STRIDE) % n_pad


# Set True after a runtime Pallas failure; solvers rebuild on the jnp
# path (the lru caches key on use_pallas, so the rebuild is a new jit).
_PALLAS_BROKEN = False


def _pallas_enabled() -> bool:
    """Fuse the per-class fill into one Mosaic kernel?  TPU-only (tests
    run the jnp path on CPU; equivalence is covered by an interpret-mode
    test), opt-out via config, auto-off after a runtime failure."""
    if _PALLAS_BROKEN or not get_config().scheduler_pallas_fill:
        return False
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.lru_cache(maxsize=16)
def _pallas_class_fill(c_pad: int, n_pad: int, r_pad: int,
                       interpret: bool = False):
    """The whole class scan as ONE Mosaic kernel: grid over classes,
    availability carried in VMEM scratch across grid steps.

    The jnp path lowers each class step to ~10 fused XLA kernels; at
    256 classes x 40 ticks that is ~10^5 sequential kernel launches
    whose fixed overheads dominate the tick (the arrays are far too
    small to be bandwidth-bound).  Here one kernel invocation per class
    does everything in VMEM — the [B, N] bucket tensors never touch
    HBM, and per-class HBM traffic is one [1, N] allocs row out.

    Same math as ``_bucket_fill_step`` with two kernel-friendly
    substitutions (both f32-exact for integer capacities < 2^24):
      * the within-bucket exclusive prefix is a lane-axis Hillis-Steele
        scan (``pltpu.roll`` + iota mask) instead of the blocked
        reshape/cumsum;
      * the bucket-prefix cumsum over B=19 entries is a strictly-lower
        triangular matmul at Precision.HIGHEST (MXU bf16 passes round
        integers like 265 — HIGHEST is required for exactness).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = _NUM_BUCKETS
    eps = 1e-6

    def kernel(counts_ref, accel_ref, shifts_ref, thr_ref,
               demand_ref, total_ref, accel_node_ref, av0_ref, cost_ref,
               av_out_ref, allocs_ref, av_s):
        c = pl.program_id(0)

        @pl.when(c == 0)
        def _init():
            av_s[...] = av0_ref[...]

        av = av_s[...]                                     # [R, N]
        total = total_ref[...]                             # [R, N]
        cnt = counts_ref[c]
        is_accel = accel_ref[c] > 0
        shift = shifts_ref[c]
        thr = thr_ref[0]
        inv = thr_ref[1]
        d = demand_ref[0]                                  # [R, 1]
        cost = cost_ref[0]                                 # [1, N]
        demanded = d > 0
        any_demand = jnp.any(demanded)
        ratios = jnp.where(demanded, av / jnp.maximum(d, eps), _BIG)
        cap = jnp.floor(jnp.min(ratios, axis=0, keepdims=True) + eps)
        cap = jnp.clip(cap, 0.0, cnt)                      # [1, N]
        util = jnp.where(total > 0,
                         (total - av) / jnp.maximum(total, eps), 0.0)
        score_d = jnp.max(jnp.where(demanded, util, -_BIG),
                          axis=0, keepdims=True)
        score_o = jnp.max(util, axis=0, keepdims=True)
        score = jnp.where(any_demand, score_d, score_o)    # [1, N]
        score = jnp.where(inv > 0, 1.0 - score, score)
        empty = jnp.max(total, axis=0, keepdims=True) <= 0.0
        accel_node = accel_node_ref[...] > 0.0             # [1, N]
        scale = _UTIL_LEVELS / jnp.maximum(1.0 - thr, eps)
        lvl = jnp.clip(jnp.floor((score - thr) * scale) + 1.0,
                       1.0, float(_UTIL_LEVELS))
        b_util = jnp.where(score < thr, 0.0, lvl)
        cost_b = jnp.floor(cost * scale + 0.5)
        bucket = jnp.clip(b_util + float(_COST_BUCKETS) + cost_b,
                          0.0, float(_COST_BUCKETS + _UTIL_LEVELS))
        bucket = jnp.where(
            jnp.logical_and(accel_node, jnp.logical_not(is_accel)),
            float(_COST_BUCKETS + _UTIL_LEVELS + 1), bucket)
        bucket = jnp.where(empty, float(B - 1), bucket).astype(jnp.int32)
        onehot = bucket == jax.lax.broadcasted_iota(
            jnp.int32, (B, n_pad), 0)
        cap_oh = jnp.where(onehot, cap, 0.0)               # [B, N]
        lane = jax.lax.broadcasted_iota(jnp.int32, (B, n_pad), 1)
        p = cap_oh
        k = 1
        while k < n_pad:
            p = p + jnp.where(lane >= k, pltpu.roll(p, k, 1), 0.0)
            k *= 2
        p_nat = p - cap_oh                                 # excl. prefix
        btotal = jnp.max(p, axis=1, keepdims=True)         # [B, 1]
        before = lane < shift
        q = jnp.sum(jnp.where(before, cap_oh, 0.0),
                    axis=1, keepdims=True)                 # [B, 1]
        row = jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)
        tri_excl = (col < row).astype(jnp.float32)
        bprefix = jax.lax.dot_general(
            tri_excl, btotal, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)            # [B, 1]
        # Rotation decomposed analytically (see _bucket_fill_step).
        prefix_bn = p_nat - q + jnp.where(before, btotal, 0.0) + bprefix
        prefix = jnp.sum(jnp.where(onehot, prefix_bn, 0.0),
                         axis=0, keepdims=True)            # [1, N]
        take = jnp.clip(cnt - prefix, 0.0, cap)
        av_s[...] = av - d * take
        allocs_ref[...] = take[None]

        @pl.when(c == c_pad - 1)
        def _fin():
            av_out_ref[...] = av_s[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(c_pad,),
        in_specs=[
            pl.BlockSpec((1, r_pad, 1), lambda c, *_: (c, 0, 0)),
            pl.BlockSpec((r_pad, n_pad), lambda c, *_: (0, 0)),
            pl.BlockSpec((1, n_pad), lambda c, *_: (0, 0)),
            pl.BlockSpec((r_pad, n_pad), lambda c, *_: (0, 0)),
            pl.BlockSpec((1, 1, n_pad), lambda c, *_: (c, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((r_pad, n_pad), lambda c, *_: (0, 0)),
            pl.BlockSpec((1, 1, n_pad), lambda c, *_: (c, 0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((r_pad, n_pad), jnp.float32)],
    )
    fn = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, n_pad), jnp.float32),
            jax.ShapeDtypeStruct((c_pad, 1, n_pad), jnp.float32),
        ],
        interpret=interpret,
    )

    def fill(av_t, total_t, demand, counts, accel_class, accel_node,
             spread_threshold, cost, invert, shifts):
        import jax.numpy as jnp
        av_out, allocs = fn(
            counts.astype(jnp.float32),
            accel_class.astype(jnp.int32),
            shifts.astype(jnp.int32),
            jnp.stack([jnp.asarray(spread_threshold, jnp.float32),
                       jnp.asarray(invert, jnp.float32)]),
            demand[:, :, None].astype(jnp.float32),
            total_t,
            accel_node.astype(jnp.float32)[None, :],
            av_t,
            cost[:, None, :].astype(jnp.float32))
        return av_out, allocs[:, 0, :]

    return fill


def _class_fill(av_t, total_t, demand, counts, accel_class, accel_node,
                spread_threshold, *, c_pad: int, n_pad: int, r_pad: int,
                use_pallas: bool, cost=None, invert=None, shifts=None):
    """Run the per-class waterfill over all classes against ``av_t``.

    ``cost`` [C, N] per-(class, node) score offsets (None = zeros),
    ``invert`` scalar flag for pack mode, ``shifts`` [C] within-bucket
    rotation offsets (None = the default per-class stride).  Returns
    (av_after [R, N], allocs [C, N]).  One fused Mosaic kernel on TPU;
    the jnp scan elsewhere (both oracle-exact)."""
    import jax
    import jax.numpy as jnp

    if cost is None:
        cost = jnp.zeros((c_pad, n_pad), jnp.float32)
    if invert is None:
        invert = jnp.float32(0.0)
    if shifts is None:
        shifts = _class_shifts(c_pad, n_pad)
    if use_pallas:
        fill = _pallas_class_fill(c_pad, n_pad, r_pad)
        return fill(av_t, total_t, demand, counts, accel_class,
                    accel_node, spread_threshold, cost, invert, shifts)
    empty = jnp.max(total_t, axis=0) <= 0

    def body(av, inputs):
        d, cnt, is_accel, shift, cost_row = inputs
        return _bucket_fill_step(av, total_t, d, cnt, is_accel, shift,
                                 cost_row, invert, accel_node, empty,
                                 spread_threshold)

    av_after, allocs = jax.lax.scan(
        body, av_t, (demand, counts, accel_class, shifts, cost), unroll=8)
    return av_after, allocs


def _pack_tick(allocs, counts_k, av_pre, demand, nnz_max):
    """On-device validation + fixed-size sparse encoding for one tick.

    Returns (packed[2*nnz_max+3], placed_c[C]).  Sparse indices are exact
    in f32 while C_pad*N_pad < 2^24 (asserted by callers).  Compaction is
    ``jnp.nonzero(size=...)`` — XLA's static-size stream compaction —
    which replaced the earlier rank-cumsum + searchsorted formulation
    (21 binary-search steps of 32k gathers each dominated the tick).
    """
    import jax.numpy as jnp

    flat_n = allocs.shape[0] * allocs.shape[1]
    usage = jnp.einsum("cn,cr->rn", allocs, demand)
    ok_cap = jnp.all(usage <= av_pre + 1e-2)
    placed_c = jnp.sum(allocs, axis=1)                     # [C]
    ok_cnt = jnp.all(placed_c <= counts_k + 0.5)
    placed = jnp.sum(placed_c)
    flat = allocs.reshape(flat_n)
    nz = flat > 0
    nnz = jnp.sum(nz.astype(jnp.int32))
    (pos,) = jnp.nonzero(nz, size=nnz_max, fill_value=flat_n)
    live = jnp.arange(nnz_max) < nnz
    posc = jnp.minimum(pos, flat_n - 1)
    idx = jnp.where(live, posc, flat_n)
    vals = jnp.where(live, flat[posc], 0.0)
    ok = ok_cap & ok_cnt & (nnz <= nnz_max)
    packed = jnp.concatenate([
        idx.astype(jnp.float32), vals,
        jnp.stack([placed, ok.astype(jnp.float32),
                   nnz.astype(jnp.float32)])])
    return packed, placed_c


# ---------------------------------------------------------------------------
# Device kernels (jit-compiled once per padded shape).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=16)
def _jit_waterfill(c_pad: int, n_pad: int, r_pad: int,
                   use_pallas: bool = False):
    import jax

    def solve(avail, total, demand, counts, accel_node, accel_class,
              spread_threshold, cost, invert, shifts):
        # avail/total: [N, R]; demand: [C, R]; counts: [C].  Transposed
        # once to the TPU-native [R, N] layout (see _bucket_fill_step).
        final_avail, allocs = _class_fill(
            avail.T, total.T, demand, counts, accel_class, accel_node,
            spread_threshold, c_pad=c_pad, n_pad=n_pad, r_pad=r_pad,
            use_pallas=use_pallas, cost=cost, invert=invert,
            shifts=shifts)
        return allocs, final_avail.T

    return jax.jit(solve)


@functools.lru_cache(maxsize=8)
def _jit_waterfill_stream(c_pad: int, n_pad: int, r_pad: int,
                          ticks: int, nnz_max: int,
                          use_pallas: bool = False):
    """K scheduler ticks in one device program, closed-loop in STATE.

    All world state is device-resident scan carry:
      * ``pending`` [C] — each tick's queue is ``pending + arrivals_k``;
        the solve places what fits and the remainder carries forward;
      * ``avail`` [R, N] — placements subtract capacity *across* ticks;
      * ``inflight`` [C, N] — placed-but-unfinished work; a geometric
        completion process with per-class rate ``rho`` releases
        ``ceil(inflight * rho)`` tasks per (class, node) each tick,
        returning their resources to ``avail`` (ceil guarantees drains
        finish: any nonzero inflight releases at least one task).

    Output is ONE packed f32 array [K, 2*nnz_max + 3] — per tick: sparse
    indices (exact in f32 while C_pad*N_pad < 2^24), sparse values, then
    (placed, ok, nnz) — so the host needs a single fetch per program.
    """
    import jax
    import jax.numpy as jnp

    assert c_pad * n_pad < (1 << 24), "sparse idx must stay exact in f32"

    def solve(avail0, total, demand, pending0, arrivals, rho, accel_node,
              accel_class, spread_threshold, cost):
        av0_t, total_t = avail0.T, total.T                 # [R, N]
        inflight0 = jnp.zeros((c_pad, n_pad), jnp.float32)

        def one_tick(carry, arrivals_k):
            pending, av, inflight = carry
            # Completions first: release resources held by finished work.
            release = jnp.minimum(jnp.ceil(inflight * rho[:, None]),
                                  inflight)                # [C, N]
            av = jnp.minimum(
                av + jnp.einsum("cn,cr->rn", release, demand), total_t)
            inflight = inflight - release
            counts_k = pending + arrivals_k
            av_after, allocs = _class_fill(
                av, total_t, demand, counts_k, accel_class, accel_node,
                spread_threshold, c_pad=c_pad, n_pad=n_pad, r_pad=r_pad,
                use_pallas=use_pallas, cost=cost)
            packed, placed_c = _pack_tick(allocs, counts_k, av, demand,
                                          nnz_max)
            pending_next = jnp.maximum(counts_k - placed_c, 0.0)
            inflight = inflight + allocs
            return (pending_next, av_after, inflight), packed

        _, out = jax.lax.scan(one_tick, (pending0, av0_t, inflight0),
                              arrivals)
        return out

    return jax.jit(solve)


@functools.lru_cache(maxsize=16)
def _jit_solve_tick(c_pad: int, n_pad: int, r_pad: int, nnz_max: int,
                    use_pallas: bool = False):
    """One runtime scheduling tick against DEVICE-RESIDENT world state.

    Unlike ``_jit_waterfill`` this takes the transposed [R, N] matrices a
    ``DeviceRuntimeSolver`` keeps on device between ticks — only the [C]
    counts vector crosses host->device, only the packed sparse assignment
    comes back (solve_stream-style validation bits included).
    """
    import jax
    import jax.numpy as jnp

    assert c_pad * n_pad < (1 << 24), "sparse idx must stay exact in f32"

    def solve(avail_t, total_t, demand, counts, accel_node, accel_class,
              spread_threshold, cost):
        _, allocs = _class_fill(
            avail_t, total_t, demand, counts, accel_class, accel_node,
            spread_threshold, c_pad=c_pad, n_pad=n_pad, r_pad=r_pad,
            use_pallas=use_pallas, cost=cost)
        packed, _ = _pack_tick(allocs, counts, avail_t, demand, nnz_max)
        return packed

    return jax.jit(solve)


@functools.lru_cache(maxsize=16)
def _jit_apply_rows(n_pad: int, r_pad: int, k_pad: int):
    """Scatter k dirty node rows into the device-resident avail matrix."""
    import jax

    def apply(avail_t, idx, rows):
        # avail_t [R, N]; idx [k]; rows [k, R].  Padding duplicates the
        # last real entry, so duplicate-index writes carry equal values.
        return avail_t.at[:, idx].set(rows.T)

    return jax.jit(apply, donate_argnums=(0,))


@functools.lru_cache(maxsize=16)
def _jit_pack_bundles(b_pad: int, n_pad: int, r_pad: int):
    """Placement-group bundle -> node solve as ONE device program.

    Bundles are count-1 demands whose placement interacts through the
    evolving (availability, used-node) carry, so the solve is a scan
    over bundle rows — still a single dispatch for the whole group
    (and the strategy semantics live in the cost, not host loops):

      * score = LeastResourceScorer best-fit (after-allocation leftover
        of the demanded resources, gcs_resource_scheduler.h:74),
      * PACK  -> ``pack_w`` > 0 bonus on already-used nodes,
        SPREAD -> ``pack_w`` < 0 penalty (soft constraints),
      * STRICT_SPREAD -> used nodes masked infeasible (hard),
      * STRICT_PACK is collapsed by the host into one composite row.

    Returns (node_idx [B] int32, ok [B] bool).  Padded bundle rows
    (empty demand) are no-ops; padded nodes (zero total) are never
    feasible.  The host validates the assignment against the exact
    quantized vectors before the 2PC prepare — kernel output never
    commits unchecked (same contract as the task tick).
    """
    import jax
    import jax.numpy as jnp

    def solve(avail, total, demand, excluded, used0, pack_w,
              strict_spread):
        eps = 1e-6
        alive = jnp.max(total, axis=1) > 0                 # [N]
        node_ok = alive & ~excluded

        def body(carry, d):
            av, used = carry
            demanded = d > 0                               # [R]
            is_real = jnp.any(demanded)
            feasible = jnp.all(av + eps >= d[None, :], axis=1) & node_ok
            feasible = jnp.where(strict_spread > 0,
                                 feasible & ~used, feasible)
            # LeastResourceScorer: mean over demanded resources of
            # 1 - leftover/have — higher = tighter fit (best fit).
            terms = jnp.where(
                demanded[None, :],
                1.0 - (av - d[None, :]) / jnp.maximum(av, 1.0), 0.0)
            nd = jnp.maximum(jnp.sum(demanded.astype(jnp.float32)), 1.0)
            sc = jnp.sum(terms, axis=1) / nd
            sc = sc + pack_w * used.astype(jnp.float32)
            sc = jnp.where(feasible, sc, -_BIG)
            best = jnp.argmax(sc).astype(jnp.int32)
            ok = is_real & (sc[best] > -_BIG / 2)
            hot = (jnp.arange(av.shape[0]) == best) & ok   # [N]
            av = av - jnp.where(hot[:, None], d[None, :], 0.0)
            used = used | hot
            return (av, used), (best, ok)

        (_, _), (idx, ok) = jax.lax.scan(body, (avail, used0), demand)
        return idx, ok

    return jax.jit(solve)


@functools.lru_cache(maxsize=16)
def _jit_sinkhorn(c_pad: int, n_pad: int, r_pad: int, iters: int):
    import jax
    import jax.numpy as jnp

    def solve(avail, total, demand, counts, accel_node, accel_class,
              spread_threshold, tau):
        eps = 1e-6
        # Feasibility + initial per-(class,node) capacity in task units.
        demanded = demand > 0                              # [C, R]
        ratios = jnp.where(demanded[:, None, :],
                           avail[None, :, :] /
                           jnp.maximum(demand[:, None, :], eps), _BIG)
        cap = jnp.floor(jnp.min(ratios, axis=2) + eps)     # [C, N]
        cap = jnp.minimum(cap, counts[:, None])
        feasible = cap > 0
        # Cost: utilization + accel penalty (same shape as waterfill).
        util = jnp.where(total > 0, (total - avail) /
                         jnp.maximum(total, eps), 0.0)     # [N, R]
        score = jnp.einsum("nr,cr->cn",
                           util, demanded.astype(jnp.float32))
        score = score / jnp.maximum(
            jnp.sum(demanded, axis=1, dtype=jnp.float32)[:, None], 1.0)
        score = jnp.where(score < spread_threshold, 0.0, score)
        score = score + (accel_node[None, :] &
                         ~accel_class[:, None]) * 1.0
        logits = jnp.where(feasible, -score / tau, -_BIG)
        # Masked-softmax transport plan, row-targets = counts.
        plan = jax.nn.softmax(logits, axis=1) * counts[:, None]  # [C, N]
        # Column capacity in "task slots" is class-dependent; approximate
        # the shared multi-resource constraint per resource: scale columns
        # so per-resource usage fits availability.
        def sinkhorn_iter(plan, _):
            usage = jnp.einsum("cn,cr->nr", plan, demand)      # [N, R]
            factor = jnp.min(
                jnp.where(usage > eps,
                          jnp.clip(avail / jnp.maximum(usage, eps), 0.0, 1.0),
                          1.0),
                axis=1)                                        # [N]
            plan = plan * factor[None, :]
            # Re-normalize rows back toward counts (never exceeding them).
            row = jnp.sum(plan, axis=1, keepdims=True)
            plan = plan * jnp.where(row > eps,
                                    jnp.minimum(counts[:, None] /
                                                jnp.maximum(row, eps),
                                                _BIG),
                                    0.0)
            plan = jnp.minimum(plan, cap)
            return plan, None

        plan, _ = jax.lax.scan(sinkhorn_iter, plan, None, length=iters)

        # Round: fill nodes per class in plan-descending order, re-checking
        # capacity against the running availability (exactness restored).
        def body(av, inputs):
            d, cnt, p = inputs
            demanded_r = d > 0
            ratios = jnp.where(demanded_r[None, :],
                               av / jnp.maximum(d[None, :], eps), _BIG)
            capn = jnp.floor(jnp.min(ratios, axis=1) + eps)
            capn = jnp.clip(capn, 0.0, cnt)
            order = jnp.argsort(-p, stable=True)
            cap_sorted = capn[order]
            prefix = jnp.cumsum(cap_sorted) - cap_sorted
            take_sorted = jnp.clip(cnt - prefix, 0.0, cap_sorted)
            alloc = jnp.zeros((n_pad,), jnp.float32).at[order].set(take_sorted)
            av = av - alloc[:, None] * d[None, :]
            return av, alloc

        final_avail, allocs = jax.lax.scan(body, avail,
                                           (demand, counts, plan))
        return allocs, final_avail

    return jax.jit(solve)


# ---------------------------------------------------------------------------
# numpy oracle (golden reference for tests).
# ---------------------------------------------------------------------------

def bucket_oracle(score: np.ndarray, accel_avoid: np.ndarray,
                  empty: np.ndarray, spread_threshold: float,
                  cost: Optional[np.ndarray] = None) -> np.ndarray:
    """Quantize scores into fill-priority buckets (same spec as device):
    the utilization mapping (flat pack zone below the threshold, 16
    quantized levels above) offset by the cost term in bucket units,
    with 16 pre-buckets below the pack zone for cost-preferred nodes."""
    thr = np.float32(spread_threshold)
    scale = np.float32(_UTIL_LEVELS) / max(np.float32(1.0) - thr,
                                           np.float32(1e-6))
    lvl = np.clip(np.floor((score - thr) * scale) + 1.0, 1.0, _UTIL_LEVELS)
    b_util = np.where(score < thr, np.float32(0.0), lvl)
    if cost is None:
        cost_b = np.float32(0.0)
    else:
        cost_b = np.floor(cost.astype(np.float32) * scale +
                          np.float32(0.5))
    bucket = np.clip(b_util + np.float32(_COST_BUCKETS) + cost_b,
                     0.0, _COST_BUCKETS + _UTIL_LEVELS)
    bucket = np.where(accel_avoid, _COST_BUCKETS + _UTIL_LEVELS + 1,
                      bucket)
    bucket = np.where(empty, _NUM_BUCKETS - 1, bucket)
    return bucket.astype(np.int32)


def waterfill_oracle(avail: np.ndarray, total: np.ndarray,
                     demand: np.ndarray, counts: np.ndarray,
                     accel_node: np.ndarray, accel_class: np.ndarray,
                     spread_threshold: float,
                     cost: Optional[np.ndarray] = None,
                     invert_util: bool = False,
                     zero_shifts: bool = False,
                     n_pad: Optional[int] = None) -> np.ndarray:
    """Pure-numpy reference of the bucketized waterfill (same semantics,
    including the per-class within-bucket rotation, the per-(class,node)
    ``cost`` offsets and the inverted-utilization pack mode).

    Float32 throughout so score/bucket boundaries match the device kernel
    bit-for-bit.  ``n_pad`` overrides the padded ring width the rotation
    wraps on — the sharded solve pads to ``_GROUP * n_shards`` instead of
    ``_GROUP``, so parity tests pass the sharded ring explicitly to pin
    bit-exactness at non-aligned ``N``."""
    avail = avail.astype(np.float32).copy()
    total = total.astype(np.float32)
    C, R = demand.shape
    N = avail.shape[0]
    if n_pad is None:
        n_pad = _round_up(max(N, 8), _GROUP)
    alloc = np.zeros((C, N), dtype=np.int64)
    eps = np.float32(1e-6)
    empty = total.max(axis=1) <= 0
    node_ids = np.arange(N)
    for c in range(C):
        d = demand[c].astype(np.float32)
        cnt = int(counts[c])
        if cnt == 0:
            continue
        demanded = d > 0
        if demanded.any():
            ratios = np.where(demanded[None, :],
                              avail / np.maximum(d[None, :], eps), _BIG)
            cap = np.floor(ratios.min(axis=1) + eps)
        else:
            cap = np.full(N, _BIG, dtype=np.float32)
        cap = np.clip(cap, 0, cnt).astype(np.int64)
        util = np.where(total > 0, (total - avail) / np.maximum(total, eps),
                        np.float32(0.0)).astype(np.float32)
        if demanded.any():
            score = np.where(demanded[None, :], util,
                             np.float32(-_BIG)).max(axis=1)
        else:
            score = util.max(axis=1)
        score = score.astype(np.float32)
        if invert_util:
            score = (np.float32(1.0) - score).astype(np.float32)
        accel_avoid = accel_node & (not accel_class[c])
        bucket = bucket_oracle(score.astype(np.float32), accel_avoid, empty,
                               spread_threshold,
                               cost=None if cost is None else cost[c])
        # Fill order: (bucket, node-id rotated by the class stride) — the
        # padded nodes carry zero capacity so only the real nodes'
        # relative rolled order matters.
        shift = 0 if zero_shifts else (c * _ROT_STRIDE) % n_pad
        rot_key = (node_ids - shift) % n_pad
        order = np.lexsort((rot_key, bucket))
        remaining = cnt
        for n in order:
            if remaining <= 0:
                break
            take = min(remaining, int(cap[n]))
            if take > 0:
                alloc[c, n] = take
                avail[n] -= take * d
                remaining -= take
    return alloc


def stream_oracle(avail: np.ndarray, total: np.ndarray, demand: np.ndarray,
                  arrivals: np.ndarray, rho: np.ndarray,
                  accel_node: np.ndarray, accel_class: np.ndarray,
                  spread_threshold: float,
                  pending0: Optional[np.ndarray] = None,
                  cost: Optional[np.ndarray] = None) -> List[np.ndarray]:
    """Numpy replay of the closed-loop tick stream (same release model as
    ``_jit_waterfill_stream``): returns each tick's dense alloc[C, N].

    Exact vs the device when all quantities are dyadic rationals (integer
    demands/counts, rho a multiple of 2^-k) under f32."""
    C, R = demand.shape
    N = avail.shape[0]
    avail = avail.astype(np.float32).copy()
    total = total.astype(np.float32)
    demand = demand.astype(np.float32)
    rho = np.broadcast_to(np.asarray(rho, dtype=np.float32), (C,))
    pending = (np.zeros(C, dtype=np.float32) if pending0 is None
               else pending0.astype(np.float32))
    inflight = np.zeros((C, N), dtype=np.float32)
    out = []
    for k in range(arrivals.shape[0]):
        release = np.minimum(np.ceil(inflight * rho[:, None]), inflight)
        avail = np.minimum(
            avail + np.einsum("cn,cr->nr", release, demand), total)
        inflight = inflight - release
        queue_k = pending + arrivals[k]
        alloc = waterfill_oracle(avail, total, demand, queue_k,
                                 accel_node, accel_class, spread_threshold,
                                 cost=cost)
        af = alloc.astype(np.float32)
        avail = avail - np.einsum("cn,cr->nr", af, demand)
        inflight = inflight + af
        pending = np.maximum(queue_k - af.sum(axis=1), 0.0)
        out.append(alloc)
    return out


# ---------------------------------------------------------------------------
# Host-side driver.
# ---------------------------------------------------------------------------

def _call_with_pallas_fallback(build_fn, args):
    """Invoke ``build_fn(use_pallas)(*args)``; on a Mosaic failure flip
    the module kill-switch and re-run on the jnp path (the jit caches
    key on use_pallas, so the rebuild is a distinct program).

    The result is blocked on INSIDE the try: TPU dispatch is
    asynchronous, so an execution-time kernel fault would otherwise
    surface at the caller's np.asarray, outside any fallback."""
    global _PALLAS_BROKEN
    import jax
    use = _pallas_enabled()
    try:
        return jax.block_until_ready(build_fn(use)(*args))
    except Exception:
        if not use:
            raise
        import logging
        logging.getLogger(__name__).exception(
            "Pallas scheduler kernel failed; falling back to the jnp "
            "path for the rest of this process")
        _PALLAS_BROKEN = True
        return build_fn(False)(*args)


class BatchSolver:
    """Groups pending specs by scheduling class, runs the device solve,
    expands the allocation back to per-task node targets."""

    def __init__(self, mode: Optional[str] = None, sinkhorn_iters: int = 8):
        self.mode = mode or "waterfill"
        self.sinkhorn_iters = sinkhorn_iters
        self._device_state = None  # set by prepare_device

    # -- raw matrix interface (used by bench + autoscaler) ---------------
    def solve_matrices(self, avail: np.ndarray, total: np.ndarray,
                       demand: np.ndarray, counts: np.ndarray,
                       accel_node: Optional[np.ndarray] = None,
                       accel_class: Optional[np.ndarray] = None,
                       spread_threshold: Optional[float] = None,
                       cost: Optional[np.ndarray] = None,
                       invert_util: bool = False,
                       zero_shifts: bool = False):
        """Returns alloc[C,N] int64 for one tick.

        ``cost`` [C, N] adds per-(class, node) score offsets (negative =
        preferred); ``invert_util`` + ``zero_shifts`` select pack mode
        (most-utilized-first, first-fit within a bucket) — the
        autoscaler's node-count bin-packing ordering.

        Above the ``solver_shard_min_nodes`` gate (and with >1 device
        visible) the solve runs node-sharded across the local mesh
        (``sharded_solve``); any sharded failure flips the process
        kill-switch and falls through to the single-device kernel."""
        import jax
        C, R = demand.shape
        N = avail.shape[0]
        accel_node, accel_class, spread_threshold = self._defaults(
            N, C, accel_node, accel_class, spread_threshold)
        if self.mode != "sinkhorn":
            from ray_tpu.scheduler import sharded_solve
            n_shards = sharded_solve.plan_shards(N)
            if n_shards > 1:
                try:
                    return sharded_solve.solve_matrices_sharded(
                        avail, total, demand, counts, accel_node,
                        accel_class, spread_threshold, cost, invert_util,
                        zero_shifts, n_shards)
                except Exception:
                    sharded_solve.mark_broken("solve_matrices")
        c_pad, n_pad, r_pad = self._pads(C, N, R)
        args = (
            _pad_to(avail.astype(np.float32), (n_pad, r_pad)),
            _pad_to(total.astype(np.float32), (n_pad, r_pad)),
            _pad_to(demand.astype(np.float32), (c_pad, r_pad)),
            _pad_to(counts.astype(np.float32), (c_pad,)),
            _pad_to(accel_node.astype(bool), (n_pad,)),
            _pad_to(accel_class.astype(bool), (c_pad,)),
        )
        if self.mode == "sinkhorn":
            if cost is not None or invert_util or zero_shifts:
                raise ValueError(
                    "cost/invert_util/zero_shifts are waterfill-only; "
                    "the sinkhorn solver does not implement the cost "
                    "matrix and silently dropping them would return a "
                    "wrong-ordering solve")
            fn = _jit_sinkhorn(c_pad, n_pad, r_pad, self.sinkhorn_iters)
            allocs, _ = fn(*args, np.float32(spread_threshold),
                           np.float32(0.1))
        else:
            cost_p = np.zeros((c_pad, n_pad), np.float32) if cost is None \
                else _pad_to(cost.astype(np.float32), (c_pad, n_pad))
            shifts = np.zeros(c_pad, np.int32) if zero_shifts else \
                np.asarray((np.arange(c_pad) * _ROT_STRIDE) % n_pad,
                           np.int32)
            allocs, _ = _call_with_pallas_fallback(
                lambda use: _jit_waterfill(c_pad, n_pad, r_pad, use),
                (*args, np.float32(spread_threshold), cost_p,
                 np.float32(1.0 if invert_util else 0.0), shifts))
        allocs = np.asarray(jax.device_get(allocs))[:C, :N]
        return np.rint(allocs).astype(np.int64)

    # -- bundle interface (GCS placement groups) -------------------------
    def solve_bundles(self, avail: np.ndarray, total: np.ndarray,
                      demand: np.ndarray, strategy: str,
                      excluded: Optional[np.ndarray] = None):
        """Bundle -> node indices for one placement group in one device
        call (``_jit_pack_bundles``).  ``demand`` is [B, R] in host
        (unsorted) order; strategy semantics ride the kernel's cost and
        masks.  Returns (node_idx [B] int64, ok [B] bool) — callers
        treat any ``~ok`` as all-or-nothing failure and re-validate
        against exact vectors before committing.

        Sharded above the ``solver_shard_min_nodes`` gate: the
        cross-shard argmax keeps the exact first-max tie-break, so the
        sharded solve is bit-identical for any N (see sharded_solve)."""
        import jax
        B, R = demand.shape
        N = avail.shape[0]
        from ray_tpu.scheduler import sharded_solve
        n_shards = sharded_solve.plan_shards(N)
        if n_shards > 1:
            try:
                return sharded_solve.solve_bundles_sharded(
                    avail, total, demand, strategy, excluded, n_shards)
            except Exception:
                sharded_solve.mark_broken("solve_bundles")
        b_pad = _round_up(max(B, 1), 8)
        n_pad = _round_up(max(N, 8), _GROUP)
        r_pad = _round_up(max(R, 1), 8)
        if excluded is None:
            excluded = np.zeros(N, dtype=bool)
        pack_w = {"PACK": 10.0, "SPREAD": -10.0}.get(strategy, 0.0)
        fn = _jit_pack_bundles(b_pad, n_pad, r_pad)
        idx, ok = fn(
            _pad_to(avail.astype(np.float32), (n_pad, r_pad)),
            _pad_to(total.astype(np.float32), (n_pad, r_pad)),
            _pad_to(demand.astype(np.float32), (b_pad, r_pad)),
            _pad_to(excluded.astype(bool), (n_pad,)),
            np.zeros(n_pad, dtype=bool),
            np.float32(pack_w),
            np.float32(1.0 if strategy == "STRICT_SPREAD" else 0.0))
        idx = np.asarray(jax.device_get(idx))[:B].astype(np.int64)
        ok = np.asarray(jax.device_get(ok))[:B].astype(bool)
        return idx, ok

    # -- device-resident tick-stream interface (used by bench) -----------
    def prepare_device(self, avail: np.ndarray, total: np.ndarray,
                       demand: np.ndarray,
                       accel_node: Optional[np.ndarray] = None,
                       accel_class: Optional[np.ndarray] = None,
                       spread_threshold: Optional[float] = None,
                       cost: Optional[np.ndarray] = None) -> None:
        """Upload the cluster world-state once (including the static
        per-(class, node) cost matrix); subsequent solve_stream calls
        ship only per-tick queue counts."""
        import jax
        C, R = demand.shape
        N = avail.shape[0]
        c_pad, n_pad, r_pad = self._pads(C, N, R)
        accel_node, accel_class, spread_threshold = self._defaults(
            N, C, accel_node, accel_class, spread_threshold)
        cost_p = np.zeros((c_pad, n_pad), np.float32) if cost is None \
            else _pad_to(cost.astype(np.float32), (c_pad, n_pad))
        dev = {
            "cost": jax.device_put(cost_p),
            "avail": jax.device_put(
                _pad_to(avail.astype(np.float32), (n_pad, r_pad))),
            "total": jax.device_put(
                _pad_to(total.astype(np.float32), (n_pad, r_pad))),
            "demand": jax.device_put(
                _pad_to(demand.astype(np.float32), (c_pad, r_pad))),
            "accel_node": jax.device_put(
                _pad_to(accel_node.astype(bool), (n_pad,))),
            "accel_class": jax.device_put(
                _pad_to(accel_class.astype(bool), (c_pad,))),
            "thr": np.float32(spread_threshold),
            "shape": (C, N, R), "pads": (c_pad, n_pad, r_pad),
        }
        jax.block_until_ready([dev["avail"], dev["total"], dev["demand"]])
        self._device_state = dev

    def solve_stream(self, arrivals: np.ndarray,
                     pending0: Optional[np.ndarray] = None,
                     nnz_max: int = 32768,
                     rho: float | np.ndarray = 0.0) -> Dict[str, np.ndarray]:
        """Run K closed-loop ticks on device.

        arrivals is [K, C]: the exogenous per-tick task arrivals per
        scheduling class.  The pending queue, the availability matrix and
        the inflight-work matrix are all device-resident scan state: each
        tick releases completed work (per-class geometric rate ``rho``),
        solves ``pending + arrivals_k`` against the EVOLVING availability
        and carries the unplaced remainder forward.  ``rho=0`` disables
        completions (pure capacity drain).  Returns sparse assignments +
        validation per tick: ``idx`` [K, nnz_max] in the PADDED flat
        space (class*N_pad + node; decode with ``expand_sparse``, which
        knows this solver's padding), ``vals`` [K, nnz_max],
        ``placed`` [K], ``ok`` [K], ``nnz`` [K]."""
        assert self._device_state is not None, "call prepare_device first"
        dev = self._device_state
        C, N, R = dev["shape"]
        c_pad, n_pad, r_pad = dev["pads"]
        K = arrivals.shape[0]
        if pending0 is None:
            pending0 = np.zeros(C, dtype=np.float32)
        arr = _pad_to(arrivals.astype(np.float32), (K, c_pad))
        pen = _pad_to(pending0.astype(np.float32), (c_pad,))
        rho_vec = _pad_to(
            np.broadcast_to(np.asarray(rho, dtype=np.float32), (C,)).copy(),
            (c_pad,))
        packed = np.asarray(_call_with_pallas_fallback(
            lambda use: _jit_waterfill_stream(c_pad, n_pad, r_pad, K,
                                              nnz_max, use),
            (dev["avail"], dev["total"], dev["demand"], pen, arr, rho_vec,
             dev["accel_node"], dev["accel_class"], dev["thr"],
             dev["cost"])))
        return {
            "idx": np.rint(packed[:, :nnz_max]).astype(np.int64),
            "vals": packed[:, nnz_max:2 * nnz_max],
            "placed": packed[:, 2 * nnz_max],
            "ok": packed[:, 2 * nnz_max + 1] > 0.5,
            "nnz": np.rint(packed[:, 2 * nnz_max + 2]).astype(np.int64),
        }

    def expand_sparse(self, idx: np.ndarray, vals: np.ndarray
                      ) -> np.ndarray:
        """Decode one tick's sparse assignment to dense alloc[C, N]."""
        assert self._device_state is not None
        C, N, R = self._device_state["shape"]
        c_pad, n_pad, _ = self._device_state["pads"]
        alloc = np.zeros((c_pad, n_pad), dtype=np.int64)
        live = idx < c_pad * n_pad
        alloc.reshape(-1)[idx[live]] = np.rint(vals[live]).astype(np.int64)
        return alloc[:C, :N]

    @staticmethod
    def _pads(C: int, N: int, R: int) -> Tuple[int, int, int]:
        return (_round_up(max(C, 1), 8), _round_up(max(N, 8), _GROUP),
                _round_up(max(R, 1), 8))

    @staticmethod
    def _defaults(N, C, accel_node, accel_class, spread_threshold):
        if accel_node is None:
            accel_node = np.zeros(N, dtype=bool)
        if accel_class is None:
            accel_class = np.zeros(C, dtype=bool)
        if spread_threshold is None:
            spread_threshold = get_config().scheduler_spread_threshold
        return accel_node, accel_class, spread_threshold

    # -- spec interface (kept for the autoscaler + as a dense fallback) ---
    def assign(self, view, specs: Sequence) -> List:
        """Per-spec node targets (None = infeasible/unassigned)."""
        from ray_tpu.scheduler.policy import SchedulingType
        node_ids, total, avail, columns = view.snapshot()
        if not node_ids:
            return [None] * len(specs)
        # Group hybrid-class specs; everything else single-task fallback.
        groups: Dict[int, List[int]] = {}
        fallback: List[int] = []
        for i, spec in enumerate(specs):
            if spec.scheduling_options.scheduling_type is SchedulingType.HYBRID:
                groups.setdefault(spec.scheduling_class, []).append(i)
            else:
                fallback.append(i)
        targets: List = [None] * len(specs)
        if groups:
            classes = list(groups.keys())
            reqs = [specs[groups[c][0]].resources for c in classes]
            demand = view.demand_matrix(reqs)
            # demand_matrix may have added columns; re-snapshot widths.
            node_ids, total, avail, columns = view.snapshot()
            if demand.shape[1] < total.shape[1]:
                demand = _pad_to(demand, (demand.shape[0], total.shape[1]))
            counts = np.array([len(groups[c]) for c in classes])
            accel_node = accelerator_node_mask(total)
            accel_class = np.array([r.uses_accelerator() for r in reqs])
            alloc = self.solve_matrices(avail, total, demand, counts,
                                        accel_node, accel_class)
            for ci, cls in enumerate(classes):
                members = groups[cls]
                k = 0
                for n in range(len(node_ids)):
                    for _ in range(int(alloc[ci, n])):
                        if k < len(members):
                            targets[members[k]] = node_ids[n]
                            k += 1
        if fallback:
            from ray_tpu.scheduler import policy as policy_mod
            for i in fallback:
                targets[i] = policy_mod.schedule(
                    view, specs[i].resources, specs[i].scheduling_options,
                    local_node_id=None)
        return targets


class DeviceRuntimeSolver:
    """Device-resident scheduling session for the RUNTIME dispatch path.

    This is what ``ClusterTaskManager._schedule_batched`` runs
    (``scheduler_backend=jax``, the default): the cluster world state
    lives on device between scheduling ticks —

      * full upload only on structural change (node joined/left, new
        resource column, capacity growth), detected via the view's
        version counter;
      * otherwise only DIRTY node rows (availability changed by local
        grants/releases or usage broadcasts since the last tick) are
        scattered in via ``_jit_apply_rows``;
      * per tick, only the [C] counts vector goes down and one packed
        sparse assignment (with solve_stream-style on-device validation
        bits) comes back.

    The solver never mutates the device availability with its own
    placements: the host view stays authoritative (``view.subtract`` on
    commit marks rows dirty, which re-syncs them next tick) — stale
    output is validated before commit and falls back exactly like
    spillback.  On ANY failure (overflow, invalid output, device error)
    ``solve`` returns None and the caller runs the native greedy path.
    """

    _NNZ_BUCKETS = (256, 2048, 16384, 131072)
    # A class row idle this many ticks is an eviction candidate when the
    # demand matrix would otherwise have to grow (growing c_cap
    # recompiles _jit_solve_tick, so eviction is strictly cheaper).
    _CLASS_IDLE_TICKS = 256
    # Hard bound on interned class rows.  Past this the tick falls back
    # to the native greedy path instead of growing without limit — a
    # single tick with >4096 *distinct live* resource shapes is outside
    # the kernel's design envelope anyway.
    _MAX_CLASS_ROWS = 4096

    def __init__(self, node_label: str = "", locality_provider=None):
        self._state: Optional[dict] = None
        # scheduling_class -> demand row.  Rows grow as classes are
        # interned and are compacted by _evict_stale_classes when growth
        # would force a recompile (see _CLASS_IDLE_TICKS).
        self._class_rows: Dict[int, int] = {}
        self._class_reqs: List = []
        self._class_last_used: Dict[int, int] = {}
        self._demand_host: Optional[np.ndarray] = None   # [c_cap, r_pad]
        self._accel_host: Optional[np.ndarray] = None    # [c_cap]
        self._demand_dev = None
        self._accel_dev = None
        self._zero_cost_dev = None                       # [c_cap, n_pad]
        # Callable(list_of_specs) -> Dict[node_id, arg_bytes]: the
        # arg-locality signal (object sizes + locations from the object
        # directory), provided by the owning ClusterTaskManager.  None
        # disables the locality cost term.
        self._locality_provider = locality_provider
        # True when the LAST solve shipped a nonzero cost matrix — the
        # caller uses it to label spillbacks (no_capacity vs
        # locality_override) honestly.
        self.last_cost_active = False
        self.stats = {"ticks": 0, "full_syncs": 0, "row_deltas": 0,
                      "fallbacks": 0, "class_evictions": 0,
                      "cost_ticks": 0, "sharded_ticks": 0,
                      "shard_fallbacks": 0}
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
        # Label by owning node: one solver per raylet, and unlabeled
        # series from several solvers would overwrite each other.
        labels = {"node": node_label} if node_label else {}

        def _collect(solver):
            for k, v in solver.stats.items():
                record_internal(f"ray_tpu.scheduler.{k}", v, **labels)
            record_internal("ray_tpu.scheduler.interned_classes",
                            len(solver._class_reqs), **labels)
        get_metrics_registry().register_collector(self, _collect)
        # Probe once: without jax the device path is permanently off —
        # a failed import is NOT cached in sys.modules, so retrying it
        # every scheduling tick would rescan sys.path on the hot path.
        import importlib.util
        self._jax_ok = importlib.util.find_spec("jax") is not None

    # -- public ----------------------------------------------------------
    def solve(self, view, specs: Sequence) -> Optional[List]:
        """Per-spec node targets, or None if the device path could not
        produce a valid assignment (caller must fall back to greedy)."""
        from ray_tpu.scheduler.policy import SchedulingType
        # Reset per call: a tick with no HYBRID groups never reaches
        # _build_cost, and a stale True from the previous tick would
        # mislabel this tick's spillbacks as locality_override.
        self.last_cost_active = False
        groups: Dict[int, List[int]] = {}
        fallback: List[int] = []
        for i, spec in enumerate(specs):
            opts = spec.scheduling_options
            if opts.scheduling_type is SchedulingType.HYBRID:
                groups.setdefault(spec.scheduling_class, []).append(i)
            else:
                fallback.append(i)
        targets: List = [None] * len(specs)
        if groups:
            if not self._jax_ok:
                self.stats["fallbacks"] += 1
                return None
            try:
                if not self._solve_groups(view, specs, groups, targets):
                    self.stats["fallbacks"] += 1
                    return None
            except Exception:
                # The session may hold a donated-away or half-synced
                # device buffer, and the view's dirty set was already
                # drained: force a full resync next tick.
                self._state = None
                self.stats["fallbacks"] += 1
                return None
        if fallback:
            from ray_tpu.scheduler import policy as policy_mod
            for i in fallback:
                targets[i] = policy_mod.schedule(
                    view, specs[i].resources, specs[i].scheduling_options,
                    local_node_id=None)
        return targets

    # -- internals -------------------------------------------------------
    def _solve_groups(self, view, specs, groups, targets) -> bool:
        self.stats["ticks"] += 1
        ver, dirty_idx, dirty_rows = view.drain_dirty()
        st = self._state
        if (st is None or ver != st["version"]
                or view.num_nodes() > st["n_pad"]
                or view.num_columns() > st["r_pad"]):
            self._full_sync(view)
            st = self._state
        elif dirty_idx:
            self._apply_deltas(dirty_idx, dirty_rows)
        if st is None or not st["node_ids"]:
            return False
        # Register any new scheduling classes (rare: classes are interned
        # resource shapes).  A class demanding an unknown resource column
        # forces the column into the view (version bump -> full resync).
        tick = self.stats["ticks"]
        for cls in groups:
            self._class_last_used[cls] = tick
        new_classes = [c for c in groups if c not in self._class_rows]
        if new_classes and (len(self._class_reqs) + len(new_classes)
                            > self._demand_host.shape[0]):
            # Growth would widen c_cap (a recompile): first try to
            # reclaim rows from classes that have gone idle.
            self._evict_stale_classes(set(groups), st)
            if (len(self._class_reqs) + len(new_classes)
                    > self._MAX_CLASS_ROWS):
                # Over the hard cap even after stale eviction: churn
                # interned >4096 classes inside the idle window.  Evict
                # LRU rows regardless of idleness — only the classes
                # live THIS tick are protected — before giving up.
                self._evict_stale_classes(set(groups), st, force_lru=True)
            if (len(self._class_reqs) + len(new_classes)
                    > self._MAX_CLASS_ROWS):
                return False
        for cls, members in groups.items():
            if cls not in self._class_rows:
                req = specs[members[0]].resources
                if any(name not in st["columns"] for name in req.names()):
                    view.demand_matrix([req])   # creates columns
                    self._full_sync(view)
                    st = self._state
                self._register_class(cls, req, st)
        c_cap = self._demand_host.shape[0]
        counts = np.zeros(c_cap, dtype=np.float32)
        for cls, members in groups.items():
            counts[self._class_rows[cls]] = len(members)
        total_q = int(counts.sum())
        nnz_bound = min(total_q, len(groups) * len(st["node_ids"]))
        nnz_max = next((b for b in self._NNZ_BUCKETS if b >= nnz_bound),
                       None)
        if nnz_max is None:
            return False
        cfg = get_config()
        cost = self._build_cost(specs, groups, st, c_cap, cfg)
        n_pad = st["n_pad"]
        if st.get("n_shards", 1) > 1:
            # Pod-sharded tick: every shard solves its node block
            # against the resident sharded world state; failure flips
            # the kill-switch so the NEXT full sync rebuilds
            # single-device (this tick falls back like spillback).
            from ray_tpu.scheduler import sharded_solve
            try:
                merged = sharded_solve.solve_tick_sharded(
                    st["avail_t"], st["total_t"], self._demand_dev,
                    counts, st["accel_node"], self._accel_dev,
                    cfg.scheduler_spread_threshold, cost, c_cap, n_pad,
                    st["r_pad"], nnz_max, st["n_shards"])
            except Exception:
                sharded_solve.mark_broken("solve_tick")
                self.stats["shard_fallbacks"] += 1
                raise
            self.stats["sharded_ticks"] += 1
            if not merged["ok"]:
                return False
            idx, vals = merged["idx"], merged["vals"]
            live = idx < c_cap * n_pad
            idx, vals = idx[live], vals[live]
        else:
            packed = np.asarray(_call_with_pallas_fallback(
                lambda use: _jit_solve_tick(c_cap, st["n_pad"],
                                            st["r_pad"], nnz_max, use),
                (st["avail_t"], st["total_t"], self._demand_dev, counts,
                 st["accel_node"], self._accel_dev,
                 np.float32(cfg.scheduler_spread_threshold), cost)))
            ok = packed[2 * nnz_max + 1] > 0.5
            if not ok:
                return False
            # Decode the sparse assignment and expand per-spec targets.
            idx = np.rint(packed[:nnz_max]).astype(np.int64)
            vals = packed[nnz_max:2 * nnz_max]
            live = idx < c_cap * n_pad
            idx, vals = idx[live], vals[live]
        alloc = np.zeros((c_cap, n_pad), dtype=np.int64)
        alloc.reshape(-1)[idx] = np.rint(vals).astype(np.int64)
        node_ids = st["node_ids"]
        n_real = len(node_ids)
        for cls, members in groups.items():
            row = alloc[self._class_rows[cls]]
            k = 0
            for n in range(n_real):
                for _ in range(int(row[n])):
                    if k < len(members):
                        targets[members[k]] = node_ids[n]
                        k += 1
        return True

    def _build_cost(self, specs, groups, st, c_cap: int, cfg):
        """Per-(class, node) cost matrix for this tick, or the cached
        device-resident zeros when no cost term is live (the common
        case — nothing extra crosses host->device then).

        Two terms, both in utilization units (1/16 = one fill bucket):
          * heterogeneity (Gavel): ``w_het * (1 - rate/max_rate)`` from
            the node throughput labels, picked per class (accelerator
            classes read the accel rate) — slower nodes fill later;
          * arg-locality (Tesserae placement quality): ``-w_loc *
            bytes_on_node / max_bytes`` aggregated over the class's
            queued specs from the object directory's size hints —
            nodes already holding the class's argument bytes fill
            first, shrinking cross-node fetches.
        """
        w_het = cfg.scheduler_het_weight
        w_loc = cfg.scheduler_locality_weight
        het = st["het_active"] and w_het > 0.0
        loc_rows: Dict[int, Dict] = {}
        if w_loc > 0.0 and self._locality_provider is not None:
            for cls, members in groups.items():
                with_args = [specs[i] for i in members
                             if getattr(specs[i], "args", None)]
                if not with_args:
                    continue
                try:
                    by_node = self._locality_provider(with_args)
                except Exception:
                    by_node = None
                if by_node:
                    loc_rows[cls] = by_node
        if not het and not loc_rows:
            self.last_cost_active = False
            return self._zero_cost_dev
        self.last_cost_active = True
        self.stats["cost_ticks"] += 1
        n_pad = st["n_pad"]
        cost = np.zeros((c_cap, n_pad), dtype=np.float32)
        if het:
            accel = self._accel_host
            cost[:] = np.where(accel[:, None], st["het_accel"][None, :],
                               st["het_cpu"][None, :]) * np.float32(w_het)
        node_index = st["node_index"]
        for cls, by_node in loc_rows.items():
            row = self._class_rows.get(cls)
            if row is None:
                continue
            top = max(by_node.values())
            if top <= 0:
                continue
            for nid, nbytes in by_node.items():
                idx = node_index.get(nid)
                if idx is not None:
                    cost[row, idx] -= np.float32(w_loc) * \
                        np.float32(nbytes / top)
        return cost

    def _full_sync(self, view):
        import jax
        self.stats["full_syncs"] += 1
        ver, node_ids, total, avail, columns = view.snapshot_versioned()
        N, R = total.shape
        prev = self._state
        # Pod-sharded residency: above the gate the world state shards
        # along the node axis across the local mesh; every shard stays
        # device-resident between ticks exactly like the single-chip
        # path (deltas scatter into the sharded array, see
        # _apply_deltas).
        from ray_tpu.scheduler import sharded_solve
        n_shards = sharded_solve.plan_shards(N)
        # Keep padded dims monotone to avoid recompiles on node churn;
        # the sharded ring additionally pads to whole groups per shard.
        n_pad = _round_up(max(N, 8), _GROUP * n_shards)
        r_pad = _round_up(max(R, 1), 8)
        if prev is not None:
            n_pad = _round_up(max(n_pad, prev["n_pad"]),
                              _GROUP * n_shards)
            r_pad = max(r_pad, prev["r_pad"])
        accel_node = accelerator_node_mask(total)
        # Per-node throughput rates (heterogeneity cost term): read once
        # per structural change from node labels.  Normalized to the
        # fleet max so homogeneous fleets cost uniformly zero; padded
        # nodes carry the max rate (zero cost — they are masked out by
        # the empty bucket anyway).
        rates_cpu = np.ones(n_pad, dtype=np.float32)
        rates_accel = np.ones(n_pad, dtype=np.float32)
        for i, nid in enumerate(node_ids):
            res = view.node_resources(nid)
            labels = getattr(res, "labels", None) or {}
            r = _label_rate(labels, NODE_THROUGHPUT_LABEL)
            rates_cpu[i] = r
            rates_accel[i] = _label_rate(
                labels, NODE_ACCEL_THROUGHPUT_LABEL, default=r)
        rates_cpu[N:] = rates_cpu[:max(N, 1)].max()
        rates_accel[N:] = rates_accel[:max(N, 1)].max()
        het_cpu = 1.0 - rates_cpu / rates_cpu.max()
        het_accel = 1.0 - rates_accel / rates_accel.max()
        if n_shards > 1:
            sh_rn = sharded_solve.node_sharding(n_shards)
            sh_n = sharded_solve.node_sharding(n_shards, ("nodes",))
        else:
            sh_rn = sh_n = None
        self._state = {
            "version": ver, "node_ids": node_ids, "columns": columns,
            "node_index": {nid: i for i, nid in enumerate(node_ids)},
            "n_pad": n_pad, "r_pad": r_pad, "n_shards": n_shards,
            "het_cpu": het_cpu.astype(np.float32),
            "het_accel": het_accel.astype(np.float32),
            "het_active": bool(het_cpu.any() or het_accel.any()),
            "avail_t": jax.device_put(
                _pad_to(avail.astype(np.float32), (n_pad, r_pad)).T.copy(),
                sh_rn),
            "total_t": jax.device_put(
                _pad_to(total.astype(np.float32), (n_pad, r_pad)).T.copy(),
                sh_rn),
            "accel_node": jax.device_put(_pad_to(accel_node, (n_pad,)),
                                         sh_n),
        }
        # Rebuild the demand matrix against the (possibly wider) column
        # mapping.
        self._rebuild_demand(columns, r_pad)

    def _rebuild_demand(self, columns: Dict[str, int], r_pad: int):
        import jax
        c_cap = max(8, _round_up(max(len(self._class_reqs), 1), 8))
        demand = np.zeros((c_cap, r_pad), dtype=np.float32)
        accel = np.zeros(c_cap, dtype=bool)
        for row, req in enumerate(self._class_reqs):
            for name, v in req.to_dict().items():
                col = columns.get(name)
                if col is not None:
                    demand[row, col] = v
            accel[row] = req.uses_accelerator()
        self._demand_host, self._accel_host = demand, accel
        n_shards = self._state["n_shards"] if self._state else 1
        if n_shards > 1:
            from ray_tpu.scheduler import sharded_solve
            rep = sharded_solve.replicated_sharding(n_shards)
            cost_sh = sharded_solve.node_sharding(n_shards)
        else:
            rep = cost_sh = None
        self._demand_dev = jax.device_put(demand, rep)
        self._accel_dev = jax.device_put(accel, rep)
        # Device-resident zero cost matrix: the common no-cost tick
        # passes this cached handle, so nothing extra crosses
        # host->device unless a locality/heterogeneity term is live.
        n_pad = self._state["n_pad"] if self._state else _GROUP
        self._zero_cost_dev = jax.device_put(
            np.zeros((c_cap, n_pad), dtype=np.float32), cost_sh)

    def _evict_stale_classes(self, keep: set, st: dict,
                             force_lru: bool = False) -> bool:
        """Compact the demand matrix by dropping rows for classes unused
        for _CLASS_IDLE_TICKS ticks (never ones in ``keep`` — the
        classes scheduling right now).  With ``force_lru`` the idle
        threshold is ignored and everything outside ``keep`` goes (the
        over-hard-cap path).  Returns True if anything moved.  Eviction
        only costs a cheap re-registration if the class ever reappears;
        it never affects correctness."""
        tick = self.stats["ticks"]
        row_to_cls = {row: c for c, row in self._class_rows.items()}
        survivors = []
        for row in range(len(self._class_reqs)):
            cls = row_to_cls[row]
            idle = tick - self._class_last_used.get(cls, tick)
            if cls in keep or (not force_lru
                               and idle < self._CLASS_IDLE_TICKS):
                survivors.append((cls, self._class_reqs[row]))
        if len(survivors) == len(self._class_reqs):
            return False
        self.stats["class_evictions"] += \
            len(self._class_reqs) - len(survivors)
        self._class_rows = {c: i for i, (c, _) in enumerate(survivors)}
        self._class_reqs = [req for _, req in survivors]
        self._class_last_used = {
            c: self._class_last_used.get(c, tick) for c, _ in survivors}
        self._rebuild_demand(st["columns"], st["r_pad"])
        return True

    def _register_class(self, cls: int, req, st: dict):
        import jax
        row = len(self._class_reqs)
        self._class_rows[cls] = row
        self._class_reqs.append(req)
        if row >= self._demand_host.shape[0]:
            self._rebuild_demand(st["columns"], st["r_pad"])
            return
        for name, v in req.to_dict().items():
            col = st["columns"].get(name)
            if col is not None:
                self._demand_host[row, col] = v
        self._accel_host[row] = req.uses_accelerator()
        # Class registration is rare; re-uploading the (small) demand
        # matrix wholesale is simpler than a device scatter.
        rep = None
        if st.get("n_shards", 1) > 1:
            from ray_tpu.scheduler import sharded_solve
            rep = sharded_solve.replicated_sharding(st["n_shards"])
        self._demand_dev = jax.device_put(self._demand_host, rep)
        self._accel_dev = jax.device_put(self._accel_host, rep)

    def _apply_deltas(self, dirty_idx: List[int], dirty_rows: np.ndarray):
        import jax
        st = self._state
        self.stats["row_deltas"] += len(dirty_idx)
        n_pad, r_pad = st["n_pad"], st["r_pad"]
        n_shards = st.get("n_shards", 1)
        if len(dirty_idx) > n_pad // 2:
            # Cheaper to re-upload than to scatter half the matrix.
            sh = None
            if n_shards > 1:
                from ray_tpu.scheduler import sharded_solve
                sh = sharded_solve.node_sharding(n_shards)
            avail = np.asarray(st["avail_t"]).T.copy()
            avail[dirty_idx, :dirty_rows.shape[1]] = dirty_rows
            st["avail_t"] = jax.device_put(avail.T.copy(), sh)
            return
        k_pad = 1
        while k_pad < len(dirty_idx):
            k_pad *= 2
        idx = np.full(k_pad, dirty_idx[-1], dtype=np.int32)
        idx[:len(dirty_idx)] = dirty_idx
        rows = np.zeros((k_pad, r_pad), dtype=np.float32)
        rows[:, :dirty_rows.shape[1]] = dirty_rows[-1]
        rows[:len(dirty_idx), :dirty_rows.shape[1]] = dirty_rows
        if n_shards > 1:
            from ray_tpu.scheduler import sharded_solve
            fn = sharded_solve._jit_sharded_apply_rows(
                n_pad, r_pad, k_pad, n_shards)
        else:
            fn = _jit_apply_rows(n_pad, r_pad, k_pad)
        st["avail_t"] = fn(st["avail_t"], idx, rows)
