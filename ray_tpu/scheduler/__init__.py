"""Scheduling kernel package.

- ``jax_backend`` — the device-resident batch solver (waterfill +
  bundle packing) every scheduler surface routes through; owns the
  single-device jit kernels and the dirty-row delta path.
- ``sharded_solve`` — the pod-sharded solve (ISSUE 17): above
  ``solver_shard_min_nodes`` the (classes x nodes) matrices shard along
  the node axis over a 1-D device mesh via ``shard_map``; falls back to
  the single-device kernel on any shard failure (kill-switch).
- ``bundle_packing`` — placement-group bundle packing strategies.
- ``policy`` / ``resources`` — host-side policy glue and resource
  vector shapes.

Submodules are imported lazily: ``jax_backend``/``sharded_solve`` pull
in jax at import time, and control-plane processes that never solve
(log monitor, dashboard) must not pay that.
"""

import importlib

_SUBMODULES = ("bundle_packing", "jax_backend", "policy", "resources",
               "sharded_solve")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
