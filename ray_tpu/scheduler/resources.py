"""Resource model — columnar (struct-of-arrays) from the ground up.

Parity targets: reference ``src/ray/raylet/scheduling/cluster_resource_data.h``
(``NodeResources`` = {total, available} FixedPoint vectors of predefined
resources + custom map, ``ResourceRequest`` same shape) and ``fixed_point.h``
(resource math on 1/10000 granularity).

TPU-first deviation: instead of per-node hash maps, the cluster view is a
dense ``[N, R]`` matrix (numpy on the control path, shipped to the TPU kernel
as-is each tick).  That makes `GetBestSchedulableNode` a vector op and the
batched bin-pack a single device call — this layout *is* the scheduler's
device ABI (SURVEY.md §3.4: demand[C,R] x avail[N,R]).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np
from ray_tpu._private.debug import diag_rlock

# Fixed-point granularity, matching reference fixed_point.h (1/10000).
FP_SCALE = 10_000

# Predefined resource columns (reference: cluster_resource_data.h predefined
# CPU/MEM/GPU/object-store-mem; we add TPU as a first-class accelerator).
CPU, MEMORY, TPU, GPU, OBJECT_STORE_MEMORY = range(5)
PREDEFINED = ["CPU", "memory", "TPU", "GPU", "object_store_memory"]
_PREDEFINED_INDEX = {name: i for i, name in enumerate(PREDEFINED)}
NUM_PREDEFINED = len(PREDEFINED)
# Accelerator columns avoided for tasks that don't need them
# (reference scheduler_avoid_gpu_nodes, ray_config_def.h:533).
ACCELERATOR_COLUMNS = (TPU, GPU)


def _quantize(value: float) -> int:
    return int(round(float(value) * FP_SCALE))


def accelerator_node_mask(total: np.ndarray) -> np.ndarray:
    """[N] bool mask of nodes carrying any accelerator column — the
    shared input of the greedy policy's avoid-accel penalty and the
    kernel's accel-avoid bucket (one definition, three schedulers)."""
    mask = np.zeros(total.shape[0], dtype=bool)
    for c in ACCELERATOR_COLUMNS:
        if c < total.shape[1]:
            mask |= total[:, c] > 0
    return mask


class ResourceRequest:
    """A task/bundle resource demand as a quantized sparse vector."""

    __slots__ = ("_items", "_key")

    def __init__(self, resources: Optional[Dict[str, float]] = None):
        items: Dict[str, int] = {}
        for name, amount in (resources or {}).items():
            q = _quantize(amount)
            if q < 0:
                raise ValueError(f"Negative resource {name}={amount}")
            if q > 0:
                items[name] = q
        self._items = items
        self._key: Tuple = tuple(sorted(items.items()))

    @property
    def key(self) -> Tuple:
        return self._key

    def is_empty(self) -> bool:
        return not self._items

    def get(self, name: str) -> float:
        return self._items.get(name, 0) / FP_SCALE

    def names(self) -> Iterable[str]:
        return self._items.keys()

    def to_dict(self) -> Dict[str, float]:
        return {k: v / FP_SCALE for k, v in self._items.items()}

    def quantized(self) -> Dict[str, int]:
        return dict(self._items)

    def uses_accelerator(self) -> bool:
        return any(self._items.get(PREDEFINED[c], 0) > 0
                   for c in ACCELERATOR_COLUMNS)

    def __eq__(self, other):
        return isinstance(other, ResourceRequest) and self._key == other._key

    def __hash__(self):
        return hash(self._key)

    def __repr__(self):
        return f"ResourceRequest({self.to_dict()})"


class NodeResources:
    """One node's {total, available} resource vectors (quantized)."""

    __slots__ = ("total", "available", "labels", "draining")

    def __init__(self, total: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None):
        self.total: Dict[str, int] = {k: _quantize(v) for k, v in total.items()
                                      if _quantize(v) > 0}
        self.available: Dict[str, int] = dict(self.total)
        self.labels = labels or {}
        self.draining = False

    def is_feasible(self, req: ResourceRequest) -> bool:
        return all(self.total.get(k, 0) >= v for k, v in req.quantized().items())

    def is_available(self, req: ResourceRequest) -> bool:
        return all(self.available.get(k, 0) >= v
                   for k, v in req.quantized().items())

    def allocate(self, req: ResourceRequest) -> bool:
        if not self.is_available(req):
            return False
        for k, v in req.quantized().items():
            self.available[k] -= v
        return True

    def release(self, req: ResourceRequest):
        for k, v in req.quantized().items():
            self.available[k] = min(self.total.get(k, 0),
                                    self.available.get(k, 0) + v)

    def copy(self) -> "NodeResources":
        """Value copy.  A NodeResources is a mutable accounting ledger
        (allocate/release), so two views must never share one instance:
        a holder that overwrites ``available`` from a snapshot (e.g. a
        usage-report merge) would erase the other's in-flight
        allocations."""
        nr = NodeResources.__new__(NodeResources)
        nr.total = dict(self.total)
        nr.available = dict(self.available)
        nr.labels = dict(self.labels)
        nr.draining = self.draining
        return nr

    def to_float_dict(self, which: str = "available") -> Dict[str, float]:
        src = self.available if which == "available" else self.total
        return {k: v / FP_SCALE for k, v in src.items()}


class ClusterResourceView:
    """Dense columnar view of all nodes' resources.

    Reference: ``ClusterResourceManager`` holds a NodeID->NodeResources map
    (``cluster_resource_manager.h``); here the authoritative copies stay in
    ``NodeResources`` (exact, quantized) and this view maintains the dense
    float32 ``total``/``avail`` matrices incrementally so every scheduling
    tick — native numpy or TPU — reads the same [N, R] buffers without
    re-packing.  Local views may be briefly stale between broadcasts
    (cluster_resource_data.h:221-227); the dispatch path re-validates with
    the exact per-node vectors before commit, mirroring spillback.
    """

    def __init__(self):
        self._lock = diag_rlock("ClusterResourceView._lock")
        self._node_ids: List = []
        self._node_index: Dict = {}
        self._nodes: Dict = {}          # node_id -> NodeResources
        self._columns: Dict[str, int] = dict(_PREDEFINED_INDEX)
        self._total = np.zeros((0, NUM_PREDEFINED), dtype=np.float32)
        self._avail = np.zeros((0, NUM_PREDEFINED), dtype=np.float32)
        self.version = 0  # bumped on structural change (nodes/columns)
        # Row indices whose availability changed since the last
        # drain_dirty() — the delta feed for the device-resident solver.
        self._dirty: set = set()
        # SUSPECT mask (suspect-before-dead failure detection): masked
        # nodes read as zero-available in every scheduling snapshot —
        # no NEW placements — while the authoritative ledgers underneath
        # stay intact, so clearing the mask restores real availability
        # instantly.  Mask flips dirty the affected rows so the
        # device-resident solver's delta feed tracks them too.
        self._masked: set = set()

    # ---- column management ---------------------------------------------
    def _column(self, name: str) -> int:
        idx = self._columns.get(name)
        if idx is None:
            idx = len(self._columns)
            self._columns[name] = idx
            pad = np.zeros((self._total.shape[0], 1), dtype=np.float32)
            self._total = np.concatenate([self._total, pad], axis=1)
            self._avail = np.concatenate([self._avail, pad.copy()], axis=1)
            self.version += 1
        return idx

    @property
    def columns(self) -> Dict[str, int]:
        return self._columns

    # ---- node membership ------------------------------------------------
    @staticmethod
    def _snapshot(resources: NodeResources):
        """Copy ``(total, available)`` off a possibly LIVE ledger.

        Raylets hand the view their actual ``NodeResources`` and keep
        mutating it from other threads (PG bundle commit/cancel adds
        and removes formatted resource keys), so iterating — or even
        ``dict()``-copying — the live dicts can die with "dictionary
        changed size during iteration".  The view's own lock cannot
        guard a foreign object; retry the copy until it lands between
        mutations (the window is a few microseconds).
        """
        for _ in range(1000):
            try:
                return dict(resources.total), dict(resources.available)
            except RuntimeError:
                continue
        return dict(resources.total), dict(resources.available)

    def add_node(self, node_id, resources: NodeResources):
        with self._lock:
            if node_id in self._node_index:
                self.update_node(node_id, resources)
                return
            total, avail = self._snapshot(resources)
            for name in total:
                self._column(name)
            row_t = np.zeros((1, len(self._columns)), dtype=np.float32)
            row_a = np.zeros((1, len(self._columns)), dtype=np.float32)
            for name, v in total.items():
                row_t[0, self._columns[name]] = v / FP_SCALE
            for name, v in avail.items():
                row_a[0, self._columns[name]] = v / FP_SCALE
            self._node_index[node_id] = len(self._node_ids)
            self._node_ids.append(node_id)
            self._nodes[node_id] = resources
            self._total = np.concatenate([self._total, row_t], axis=0)
            self._avail = np.concatenate([self._avail, row_a], axis=0)
            self.version += 1

    def remove_node(self, node_id):
        with self._lock:
            idx = self._node_index.pop(node_id, None)
            if idx is None:
                return
            self._node_ids.pop(idx)
            self._nodes.pop(node_id, None)
            self._total = np.delete(self._total, idx, axis=0)
            self._avail = np.delete(self._avail, idx, axis=0)
            for nid, i in list(self._node_index.items()):
                if i > idx:
                    self._node_index[nid] = i - 1
            # Remap dirty row indices past the removed row (stale indices
            # would make drain_dirty read out of bounds).
            self._dirty = {i - 1 if i > idx else i
                           for i in self._dirty if i != idx}
            self.version += 1

    def update_node(self, node_id, resources: NodeResources):
        with self._lock:
            idx = self._node_index.get(node_id)
            if idx is None:
                self.add_node(node_id, resources)
                return
            self._nodes[node_id] = resources
            total, avail = self._snapshot(resources)
            for name in total:
                self._column(name)
            self._total[idx, :] = 0.0
            self._avail[idx, :] = 0.0
            for name, v in total.items():
                self._total[idx, self._columns[name]] = v / FP_SCALE
            for name, v in avail.items():
                self._avail[idx, self._columns[name]] = v / FP_SCALE
            # Totals changed: structural for the device mirror.
            self.version += 1

    def update_available(self, node_id, available: Dict[str, float]):
        """Apply a resource-usage broadcast for one node."""
        with self._lock:
            idx = self._node_index.get(node_id)
            if idx is None:
                return
            node = self._nodes[node_id]
            node.available = {k: _quantize(v) for k, v in available.items()}
            self._avail[idx, :] = 0.0
            for name, v in available.items():
                if name in self._columns:
                    self._avail[idx, self._columns[name]] = v
            self._dirty.add(idx)

    # ---- scheduling-side mutation (dirty local view) --------------------
    def subtract(self, node_id, req: ResourceRequest) -> bool:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.allocate(req):
                return False
            idx = self._node_index[node_id]
            for name, v in req.quantized().items():
                self._avail[idx, self._columns[name]] -= v / FP_SCALE
            self._dirty.add(idx)
            return True

    def add_back(self, node_id, req: ResourceRequest):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return
            node.release(req)
            idx = self._node_index[node_id]
            for name, v in req.quantized().items():
                col = self._columns[name]
                self._avail[idx, col] = min(
                    self._total[idx, col],
                    self._avail[idx, col] + v / FP_SCALE)
            self._dirty.add(idx)

    # ---- suspect masking ------------------------------------------------
    def set_masked(self, node_ids) -> None:
        """Replace the suspect mask.  Affected rows (newly masked OR
        newly cleared) are dirtied so both the snapshot consumers and
        the device-resident delta feed converge on the new mask."""
        with self._lock:
            new = set(node_ids)
            for nid in new ^ self._masked:
                idx = self._node_index.get(nid)
                if idx is not None:
                    self._dirty.add(idx)
            self._masked = new

    def masked_nodes(self) -> set:
        with self._lock:
            return set(self._masked)

    def _masked_zero(self, avail_copy: np.ndarray) -> np.ndarray:
        """Zero masked rows in an avail COPY (callers own the copy; the
        authoritative matrix is never touched)."""
        for nid in self._masked:
            idx = self._node_index.get(nid)
            if idx is not None:
                avail_copy[idx, :] = 0.0
        return avail_copy

    # ---- dense snapshot (the device ABI) --------------------------------
    def snapshot(self):
        """Return (node_ids, total[N,R], avail[N,R], columns) — the exact
        matrices the TPU kernel consumes.  Masked (suspect) rows read
        zero-available."""
        with self._lock:
            return (list(self._node_ids), self._total.copy(),
                    self._masked_zero(self._avail.copy()),
                    dict(self._columns))

    def snapshot_versioned(self):
        """snapshot() plus the structural version, read atomically —
        the full-upload path of the device-resident solver."""
        with self._lock:
            return (self.version, list(self._node_ids), self._total.copy(),
                    self._masked_zero(self._avail.copy()),
                    dict(self._columns))

    def drain_dirty(self):
        """Atomically take (version, dirty row indices, their current
        avail rows) and clear the dirty set.  Rows re-dirtied by
        concurrent mutations after this call are picked up next drain —
        values are always read fresh, so deltas never go backwards.
        Masked (suspect) rows ship as zero, like the snapshots."""
        with self._lock:
            if not self._dirty:
                return self.version, [], None
            idx = sorted(self._dirty)
            self._dirty.clear()
            rows = self._avail[idx, :].copy()
            if self._masked:
                masked_idx = {self._node_index.get(nid)
                              for nid in self._masked}
                for j, i in enumerate(idx):
                    if i in masked_idx:
                        rows[j, :] = 0.0
            return self.version, idx, rows

    def num_columns(self) -> int:
        with self._lock:
            return len(self._columns)

    def demand_matrix(self, requests: List[ResourceRequest]) -> np.ndarray:
        """Pack demands into [C, R] aligned with this view's columns."""
        with self._lock:
            mat = np.zeros((len(requests), len(self._columns)),
                           dtype=np.float32)
            for i, req in enumerate(requests):
                for name, v in req.quantized().items():
                    mat[i, self._column(name)] = v / FP_SCALE
            return mat

    # ---- queries --------------------------------------------------------
    def node_resources(self, node_id) -> Optional[NodeResources]:
        with self._lock:
            return self._nodes.get(node_id)

    def node_ids(self) -> List:
        with self._lock:
            return list(self._node_ids)

    def num_nodes(self) -> int:
        with self._lock:
            return len(self._node_ids)

    def is_feasible_anywhere(self, req: ResourceRequest) -> bool:
        with self._lock:
            return any(n.is_feasible(req) for n in self._nodes.values())

    def total_cluster_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            for n in self._nodes.values():
                for k, v in n.total.items():
                    out[k] = out.get(k, 0.0) + v / FP_SCALE
            return out

    def available_cluster_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            for n in self._nodes.values():
                for k, v in n.available.items():
                    out[k] = out.get(k, 0.0) + v / FP_SCALE
            return out
