"""Scheduling policies — where a task/bundle should run.

Parity targets:
  * ``HybridSchedulingPolicy::Schedule`` (reference
    ``src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.cc:139``):
    traversal order [local node, others sorted by id]; score =
    critical-resource utilization truncated below
    ``scheduler_spread_threshold`` (ray_config_def.h:138); prefer
    available > feasible; accelerator nodes avoided for CPU-only work
    (ray_config_def.h:533).
  * ``SchedulingType {HYBRID, SPREAD, RANDOM, NODE_AFFINITY}`` enum +
    ``CompositeSchedulingPolicy`` dispatch (policy/scheduling_options.h:27,
    composite_scheduling_policy.h:28-44) — **the plugin point the TPU batch
    backend registers into** (`scheduler_backend=jax`, SURVEY.md §5.6).

TPU-first deviation: scoring is vectorized over the dense [N, R] columnar
view rather than a per-node loop, so single-task scheduling is a numpy op
and the batched path (ray_tpu.scheduler.jax_backend) shares the exact same
inputs.
"""

from __future__ import annotations

import enum
import random
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ray_tpu._private.config import get_config
from ray_tpu.scheduler.resources import (
    ClusterResourceView,
    ResourceRequest,
    accelerator_node_mask,
)


class SchedulingType(enum.Enum):
    HYBRID = "hybrid"
    SPREAD = "spread"
    RANDOM = "random"
    NODE_AFFINITY = "node_affinity"
    JAX_BATCH = "jax_batch"


@dataclass
class SchedulingOptions:
    """Per-request scheduling options (scheduling_options.h parity)."""

    scheduling_type: SchedulingType = SchedulingType.HYBRID
    spread_threshold: float = field(
        default_factory=lambda: get_config().scheduler_spread_threshold)
    avoid_local_node: bool = False
    require_node_available: bool = False
    avoid_accelerator_nodes: bool = field(
        default_factory=lambda: get_config().scheduler_avoid_tpu_nodes)
    node_affinity_node_id: Optional[object] = None
    node_affinity_soft: bool = False

    @classmethod
    def hybrid(cls, **kw):
        return cls(scheduling_type=SchedulingType.HYBRID, **kw)

    @classmethod
    def spread(cls, **kw):
        return cls(scheduling_type=SchedulingType.SPREAD, **kw)

    @classmethod
    def random(cls, **kw):
        return cls(scheduling_type=SchedulingType.RANDOM, **kw)

    @classmethod
    def affinity(cls, node_id, soft=False):
        return cls(scheduling_type=SchedulingType.NODE_AFFINITY,
                   node_affinity_node_id=node_id, node_affinity_soft=soft)


def _masks(view: ClusterResourceView, req: ResourceRequest,
           options: SchedulingOptions):
    """Vectorized feasible/available masks + utilization scores.

    Returns (node_ids, available_mask[N], feasible_mask[N], score[N]) where
    score is the post-placement critical-resource utilization
    (hybrid_scheduling_policy.cc:100-133), truncated below spread_threshold.
    """
    node_ids, total, avail, columns = view.snapshot()
    n = len(node_ids)
    if n == 0:
        return node_ids, np.zeros(0, bool), np.zeros(0, bool), np.zeros(0)
    demand = np.zeros(total.shape[1], dtype=np.float32)
    for name, v in req.to_dict().items():
        col = columns.get(name)
        if col is None:
            # No node in this view has ever offered the resource:
            # infeasible everywhere.
            return node_ids, np.zeros(n, bool), np.zeros(n, bool), \
                np.zeros(n, dtype=np.float32)
        demand[col] = v

    eps = 1e-6
    feasible = (total + eps >= demand).all(axis=1)
    available = (avail + eps >= demand).all(axis=1)

    # Suspect nodes (missed-beats grace) take no NEW placements at all:
    # excluded from BOTH masks — leaving them merely unavailable would
    # let the feasible-fallback branch still pick them.
    masked = view.masked_nodes()
    if masked:
        for i, nid in enumerate(node_ids):
            if nid in masked:
                feasible[i] = False
                available[i] = False

    # Post-placement utilization per resource, max over demanded resources.
    with np.errstate(divide="ignore", invalid="ignore"):
        used_after = np.clip(total - avail + demand, 0.0, None)
        util = np.where(total > 0, used_after / np.maximum(total, eps), 0.0)
    demanded_cols = demand > 0
    if demanded_cols.any():
        score = util[:, demanded_cols].max(axis=1)
    else:
        # Pure control tasks score by overall utilization to still pack.
        score = util.max(axis=1) if util.size else np.zeros(n)
    score = np.where(score < options.spread_threshold, 0.0, score)

    # Avoid accelerator nodes for non-accelerator work: add a soft penalty
    # so they rank last among equals (reference .cc:143-165 hard-skips when
    # alternatives exist; penalty + argsort gives the same preference).
    if options.avoid_accelerator_nodes and not req.uses_accelerator():
        accel = accelerator_node_mask(total)
        score = score + accel.astype(np.float32) * 1.0
    return node_ids, available, feasible, score


def schedule(view: ClusterResourceView, req: ResourceRequest,
             options: SchedulingOptions, local_node_id=None):
    """Composite dispatch (composite_scheduling_policy.h:28-44)."""
    t = options.scheduling_type
    if t is SchedulingType.NODE_AFFINITY:
        return _schedule_affinity(view, req, options)
    if t is SchedulingType.RANDOM:
        return _schedule_random(view, req, options)
    if t is SchedulingType.SPREAD:
        return _schedule_spread(view, req, options, local_node_id)
    return _schedule_hybrid(view, req, options, local_node_id)


def _schedule_hybrid(view, req, options, local_node_id):
    node_ids, available, feasible, score = _masks(view, req, options)
    if not len(node_ids):
        return None
    # Traversal order: local first, then others sorted by id (.cc:35-73).
    order = np.arange(len(node_ids))
    keys = sorted(range(len(node_ids)),
                  key=lambda i: (node_ids[i] != local_node_id, node_ids[i]))
    order = np.array(keys)
    rank = np.empty(len(node_ids))
    rank[order] = np.arange(len(node_ids))
    if options.avoid_local_node and local_node_id in node_ids:
        li = node_ids.index(local_node_id)
        available = available.copy()
        available[li] = False
    # Prefer available over feasible; among available pick min (score, rank).
    cand = np.nonzero(available)[0]
    if len(cand) == 0:
        if options.require_node_available:
            return None
        cand = np.nonzero(feasible)[0]
        if len(cand) == 0:
            return None
    best = min(cand, key=lambda i: (score[i], rank[i]))
    return node_ids[best]


def _schedule_spread(view, req, options, local_node_id):
    # Round-robin over available nodes (scheduling_policy.cc Spread):
    # pick the available node with the lowest utilization, random tie-break.
    node_ids, available, feasible, score = _masks(view, req, options)
    cand = np.nonzero(available)[0]
    if len(cand) == 0:
        cand = np.nonzero(feasible)[0]
        if len(cand) == 0 or options.require_node_available:
            return None
    min_score = score[cand].min()
    ties = [i for i in cand if score[i] <= min_score + 1e-9]
    return node_ids[random.choice(ties)]


def _schedule_random(view, req, options):
    node_ids, available, feasible, _ = _masks(view, req, options)
    cand = np.nonzero(available)[0]
    if len(cand) == 0:
        cand = np.nonzero(feasible)[0]
        if len(cand) == 0:
            return None
    return node_ids[random.choice(list(cand))]


def _schedule_affinity(view, req, options):
    target = options.node_affinity_node_id
    node = view.node_resources(target)
    if node is not None and node.is_available(req):
        return target
    if node is not None and node.is_feasible(req) and not options.node_affinity_soft:
        return target  # queue on the target; it will run when resources free
    if options.node_affinity_soft:
        return _schedule_hybrid(view, req,
                                SchedulingOptions.hybrid(), None)
    return None
