"""Pod-sharded scheduling solve: the node axis across local devices.

The single-device kernels in ``jax_backend`` solve the (classes x
nodes) waterfill / tick / bundle-pack on ONE chip.  This module shards
the NODE axis across every visible device with ``shard_map`` over a 1-D
``jax.sharding.Mesh`` (axis name ``"nodes"``), so each device owns a
contiguous block of ``n_local = n_pad / n_shards`` node columns and the
whole padded ring is the shard-major concatenation of the blocks.

Reduction semantics per bucket step (see ``_sharded_fill_step``):

  * every shard computes the SAME per-node cap/score/bucket math as
    ``_bucket_fill_step`` on its local columns (elementwise — bitwise
    identical to the single-device kernel);
  * the within-bucket exclusive prefix splits into a shard-local
    two-level blocked prefix plus a cross-shard exclusive offset:
    ``all_gather`` of the [B] per-shard bucket totals gives every shard
    the full [n_shards, B] table, from which it takes its own exclusive
    prefix (offset) and the global bucket totals S;
  * the rotation decomposition (P/Q/S from ``_bucket_fill_step``) needs
    Q[b] = global prefix at the rotation start: exactly one shard owns
    that column, contributes its value, and a ``psum`` replicates it;
  * the wrap term compares GLOBAL lane index (shard_lo + local lane)
    against the shift, so rotated fill order is identical to the
    single-device ring.

All sums are integer-valued f32, so as long as per-bucket totals stay
below 2**24 every reduction is exact in ANY association order:
sharded output is BIT-identical to the single-device kernel whenever
both use the same padded ring width (``n_pad``).  Because this module
pads N to a multiple of ``_GROUP * n_shards`` while the single-device
path pads to ``_GROUP``, a non-aligned N widens the ring and the
per-class rotation ``(c * _ROT_STRIDE) % n_pad`` lands elsewhere —
allocations then differ only in within-bucket tie-break order
(feasibility-parity; the parity tests pin BIT-parity against the numpy
oracle evaluated on the sharded ring width, and against the
single-device kernel on aligned shapes).

Bundle packing's cross-shard argmax keeps the exact ``jnp.argmax``
first-max tie-break: each shard reports (local first-max value, local
index); the winner is the FIRST shard attaining the global max, which
in shard-major concatenation order is precisely the global first-max.

Failure containment mirrors the Pallas kill-switch: any sharded-solve
error flips ``_SHARD_BROKEN`` for the process and callers re-route to
the single-device path (``plan_shards`` returns 1 from then on).
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import numpy as np

from ray_tpu._private.config import get_config
from ray_tpu.scheduler.jax_backend import (
    _BIG, _COST_BUCKETS, _GROUP, _NUM_BUCKETS, _ROT_STRIDE, _UTIL_LEVELS,
    _pad_to, _round_up)

logger = logging.getLogger(__name__)

_AXIS = "nodes"

# Flipped on the first sharded-solve failure; plan_shards then pins the
# process to the single-device path (same pattern as _PALLAS_BROKEN).
_SHARD_BROKEN = False
_SHARD_BROKEN_WHY: Optional[str] = None


def mark_broken(why: str) -> None:
    global _SHARD_BROKEN, _SHARD_BROKEN_WHY
    if not _SHARD_BROKEN:
        logger.exception(
            "sharded solve failed (%s); single-device path for the rest "
            "of this process", why)
    _SHARD_BROKEN = True
    _SHARD_BROKEN_WHY = why


def reset_broken() -> None:
    """Test hook: re-arm the sharded path after a deliberate failure."""
    global _SHARD_BROKEN, _SHARD_BROKEN_WHY
    _SHARD_BROKEN = False
    _SHARD_BROKEN_WHY = None


def plan_shards(n_nodes: int) -> int:
    """Shard count for a solve over ``n_nodes`` nodes (1 = don't shard).

    Gate: ``solver_shard_backend`` ("off" never, "force" whenever >1
    device, "auto" only at ``solver_shard_min_nodes`` scale — below
    that the collective latency outweighs the per-shard shrink), the
    process kill-switch, and the visible device count.
    """
    if _SHARD_BROKEN:
        return 1
    cfg = get_config()
    mode = cfg.solver_shard_backend
    if mode == "off":
        return 1
    if mode != "force" and n_nodes < cfg.solver_shard_min_nodes:
        return 1
    try:
        import jax
        n = len(jax.devices())
    except Exception:
        return 1
    return n if n > 1 else 1


def pads_sharded(C: int, N: int, R: int, n_shards: int):
    """Like ``BatchSolver._pads`` but the node ring is padded so every
    shard owns a whole number of 128-lane groups."""
    return (_round_up(max(C, 1), 8),
            _round_up(max(N, 8), _GROUP * n_shards),
            _round_up(max(R, 1), 8))


@functools.lru_cache(maxsize=4)
def _mesh(n_shards: int):
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < n_shards:
        raise ValueError(
            f"mesh wants {n_shards} devices, only {len(devs)} visible")
    return Mesh(np.array(devs[:n_shards]), axis_names=(_AXIS,))


def node_sharding(n_shards: int, spec_axes=(None, _AXIS)):
    """NamedSharding placing the node axis across the mesh (default:
    [R, N] layout — nodes on axis 1)."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(_mesh(n_shards), PartitionSpec(*spec_axes))


def replicated_sharding(n_shards: int):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(_mesh(n_shards), PartitionSpec())


# ---------------------------------------------------------------------------
# Per-class fill with cross-shard prefix reduction.
# ---------------------------------------------------------------------------

def _sharded_fill_step(av, total, d, cnt, is_accel, shift, cost_row,
                       invert, accel_node, empty, spread_threshold,
                       *, n_shards: int):
    """One class's waterfill step on ONE shard's [R, n_local] block.

    Local math is the verbatim ``_bucket_fill_step`` formulation; only
    the prefix acquires the cross-shard offset / Q / wrap corrections
    described in the module docstring.  Returns (new_av, take[n_local]).
    """
    import jax
    import jax.numpy as jnp

    eps = 1e-6
    n_loc = av.shape[1]
    demanded = d > 0                                       # [R]
    any_demand = jnp.any(demanded)
    ratios = jnp.where(demanded[:, None],
                       av / jnp.maximum(d[:, None], eps), _BIG)
    cap = jnp.floor(jnp.min(ratios, axis=0) + eps)         # [n_loc]
    cap = jnp.clip(cap, 0.0, cnt)
    util = jnp.where(total > 0, (total - av) / jnp.maximum(total, eps), 0.0)
    score_demanded = jnp.max(
        jnp.where(demanded[:, None], util, -_BIG), axis=0)
    score_overall = jnp.max(util, axis=0)
    score = jnp.where(any_demand, score_demanded, score_overall)
    score = jnp.where(invert > 0, 1.0 - score, score)
    scale = _UTIL_LEVELS / jnp.maximum(1.0 - spread_threshold, eps)
    lvl = jnp.clip(
        jnp.floor((score - spread_threshold) * scale) + 1.0,
        1.0, float(_UTIL_LEVELS))
    b_util = jnp.where(score < spread_threshold, 0.0, lvl)
    cost_b = jnp.floor(cost_row * scale + 0.5)
    bucket = jnp.clip(b_util + float(_COST_BUCKETS) + cost_b,
                      0.0, float(_COST_BUCKETS + _UTIL_LEVELS))
    bucket = jnp.where(jnp.logical_and(accel_node, ~is_accel),
                       float(_COST_BUCKETS + _UTIL_LEVELS + 1), bucket)
    bucket = jnp.where(empty, float(_NUM_BUCKETS - 1), bucket)
    bucket = bucket.astype(jnp.int32)
    onehot = (bucket[None, :] ==
              jnp.arange(_NUM_BUCKETS, dtype=jnp.int32)[:, None])
    cap_oh = jnp.where(onehot, cap[None, :], 0.0)          # [B, n_loc]
    # Shard-local two-level blocked prefix (identical structure to the
    # single-device kernel over this shard's groups).
    g = cap_oh.reshape(_NUM_BUCKETS, n_loc // _GROUP, _GROUP)
    gsum = jnp.sum(g, axis=2)                              # [B, G_loc]
    gprefix = jnp.cumsum(gsum, axis=1) - gsum
    tri = jnp.triu(jnp.ones((_GROUP, _GROUP), jnp.float32), k=1)
    within = jax.lax.dot_general(
        g, tri, (((2,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)
    p_loc = (within + gprefix[:, :, None]).reshape(_NUM_BUCKETS, n_loc)
    s_loc = jnp.sum(gsum, axis=1)                          # [B] shard total
    # Cross-shard reduction: every shard sees the full per-shard bucket
    # totals, takes its own exclusive offset and the global totals S.
    gathered = jax.lax.all_gather(s_loc, _AXIS)            # [n_shards, B]
    me = jax.lax.axis_index(_AXIS)
    shard_off = jnp.sum(
        jnp.where(jnp.arange(n_shards)[:, None] < me, gathered, 0.0),
        axis=0)                                            # [B] exclusive
    btotal = jnp.sum(gathered, axis=0)                     # [B] (= S)
    p_nat = p_loc + shard_off[:, None]                     # global prefix
    # Q[b] = global prefix at the rotation start column: owned by
    # exactly one shard, replicated by psum.
    lo = me * n_loc
    shift_loc = shift - lo
    own = (shift_loc >= 0) & (shift_loc < n_loc)
    q_piece = jax.lax.dynamic_slice_in_dim(
        p_nat, jnp.clip(shift_loc, 0, n_loc - 1), 1, axis=1)[:, 0]
    q_at_shift = jax.lax.psum(
        jnp.where(own, q_piece, 0.0), _AXIS)               # [B] (= Q)
    bprefix = jnp.cumsum(btotal) - btotal
    wrap = jnp.where(lo + jnp.arange(n_loc) < shift,
                     btotal[:, None], 0.0)                 # [B, n_loc]
    prefix_bn = p_nat - q_at_shift[:, None] + wrap + bprefix[:, None]
    prefix = jnp.sum(jnp.where(onehot, prefix_bn, 0.0), axis=0)
    take = jnp.clip(cnt - prefix, 0.0, cap)
    av = av - take[None, :] * d[:, None]
    return av, take


def _sharded_class_fill(av_t, total_t, demand, counts, accel_class,
                        accel_node, spread_threshold, cost, invert,
                        shifts, *, n_shards: int):
    """Scan the sharded fill over all classes (runs INSIDE shard_map:
    av_t/total_t/accel_node/cost are this shard's local blocks)."""
    import jax
    import jax.numpy as jnp

    empty = jnp.max(total_t, axis=0) <= 0

    def body(av, xs):
        d, cnt, is_accel, shift, cost_row = xs
        return _sharded_fill_step(
            av, total_t, d, cnt, is_accel, shift, cost_row, invert,
            accel_node, empty, spread_threshold, n_shards=n_shards)

    av_after, allocs = jax.lax.scan(
        body, av_t, (demand, counts, accel_class, shifts, cost), unroll=8)
    return av_after, allocs


def _sharded_pack_tick(allocs, counts_k, av_pre, demand, nnz_max,
                       n_pad, *, n_shards: int):
    """Per-shard validation + sparse encoding with GLOBAL flat indices.

    Validation bits are reduced across shards (psum) so every shard's
    packed row carries the same (placed, ok); the nnz slot stays
    per-shard and the host sums it while merging rows.
    """
    import jax
    import jax.numpy as jnp

    c_pad, n_loc = allocs.shape
    usage = jnp.einsum("cn,cr->rn", allocs, demand)
    bad_cap = jnp.any(usage > av_pre + 1e-2)
    ok_cap = jax.lax.psum(bad_cap.astype(jnp.float32), _AXIS) == 0
    placed_c = jax.lax.psum(jnp.sum(allocs, axis=1), _AXIS)    # [C] global
    ok_cnt = jnp.all(placed_c <= counts_k + 0.5)
    placed = jnp.sum(placed_c)
    me = jax.lax.axis_index(_AXIS)
    lo = me * n_loc
    flat = allocs.reshape(c_pad * n_loc)
    nz = flat > 0
    nnz_loc = jnp.sum(nz.astype(jnp.int32))
    (pos,) = jnp.nonzero(nz, size=nnz_max, fill_value=c_pad * n_loc)
    live = jnp.arange(nnz_max) < nnz_loc
    posc = jnp.minimum(pos, c_pad * n_loc - 1)
    gidx = (posc // n_loc) * n_pad + lo + (posc % n_loc)
    idx = jnp.where(live, gidx, c_pad * n_pad)
    vals = jnp.where(live, flat[posc], 0.0)
    overflow = jax.lax.psum(
        (nnz_loc > nnz_max).astype(jnp.float32), _AXIS) > 0
    ok = ok_cap & ok_cnt & ~overflow
    return jnp.concatenate([
        idx.astype(jnp.float32), vals,
        jnp.stack([placed, ok.astype(jnp.float32),
                   nnz_loc.astype(jnp.float32)])])


# ---------------------------------------------------------------------------
# Jitted sharded programs (cached per padded shape x shard count).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=16)
def _jit_sharded_waterfill(c_pad: int, n_pad: int, r_pad: int,
                           n_shards: int):
    """Sharded twin of ``_jit_waterfill`` ([N, R] in, allocs [C, N] out)."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n_shards)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(_AXIS, None), P(_AXIS, None), P(), P(), P(_AXIS),
                  P(), P(), P(None, _AXIS), P(), P()),
        out_specs=(P(None, _AXIS), P(_AXIS, None)),
        check_rep=False)
    def solve(avail, total, demand, counts, accel_node, accel_class,
              spread_threshold, cost, invert, shifts):
        av_after, allocs = _sharded_class_fill(
            avail.T, total.T, demand, counts, accel_class, accel_node,
            spread_threshold, cost, invert, shifts, n_shards=n_shards)
        return allocs, av_after.T

    return jax.jit(solve)


@functools.lru_cache(maxsize=16)
def _jit_sharded_solve_tick(c_pad: int, n_pad: int, r_pad: int,
                            nnz_max: int, n_shards: int):
    """Sharded twin of ``_jit_solve_tick``: device-resident sharded
    [R, N] world state in, per-shard packed rows [n_shards, 2*nnz+3]
    out (merge with ``merge_packed``)."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    assert c_pad * n_pad < (1 << 24), "sparse idx must stay exact in f32"
    mesh = _mesh(n_shards)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, _AXIS), P(None, _AXIS), P(), P(), P(_AXIS),
                  P(), P(), P(None, _AXIS)),
        out_specs=P(_AXIS, None),
        check_rep=False)
    def solve(avail_t, total_t, demand, counts, accel_node, accel_class,
              spread_threshold, cost):
        shifts = (np.arange(c_pad, dtype=np.int32) * _ROT_STRIDE) % n_pad
        import jax.numpy as jnp
        _, allocs = _sharded_class_fill(
            avail_t, total_t, demand, counts, accel_class, accel_node,
            spread_threshold, cost, jnp.float32(0.0),
            jnp.asarray(shifts), n_shards=n_shards)
        packed = _sharded_pack_tick(allocs, counts, avail_t, demand,
                                    nnz_max, n_pad, n_shards=n_shards)
        return packed[None, :]

    return jax.jit(solve)


@functools.lru_cache(maxsize=16)
def _jit_sharded_pack_bundles(b_pad: int, n_pad: int, r_pad: int,
                              n_shards: int):
    """Sharded twin of ``_jit_pack_bundles``: per-bundle cross-shard
    argmax with the exact first-max tie-break (see module docstring).
    Outputs are replicated; the host reads shard row 0."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n_shards)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(_AXIS, None), P(_AXIS, None), P(), P(_AXIS), P(_AXIS),
                  P(), P()),
        out_specs=(P(_AXIS, None), P(_AXIS, None)),
        check_rep=False)
    def solve(avail, total, demand, excluded, used0, pack_w,
              strict_spread):
        import jax.numpy as jnp
        eps = 1e-6
        n_loc = avail.shape[0]
        me = jax.lax.axis_index(_AXIS)
        alive = jnp.max(total, axis=1) > 0
        node_ok = alive & ~excluded

        def body(carry, d):
            av, used = carry
            demanded = d > 0
            is_real = jnp.any(demanded)
            feasible = jnp.all(av + eps >= d[None, :], axis=1) & node_ok
            feasible = jnp.where(strict_spread > 0,
                                 feasible & ~used, feasible)
            terms = jnp.where(
                demanded[None, :],
                1.0 - (av - d[None, :]) / jnp.maximum(av, 1.0), 0.0)
            nd = jnp.maximum(jnp.sum(demanded.astype(jnp.float32)), 1.0)
            sc = jnp.sum(terms, axis=1) / nd
            sc = sc + pack_w * used.astype(jnp.float32)
            sc = jnp.where(feasible, sc, -_BIG)
            loc_best = jnp.argmax(sc).astype(jnp.int32)
            loc_val = sc[loc_best]
            vals_all = jax.lax.all_gather(loc_val, _AXIS)   # [n_shards]
            idxs_all = jax.lax.all_gather(loc_best, _AXIS)
            win = jnp.argmax(vals_all).astype(jnp.int32)    # first shard
            best = win * n_loc + idxs_all[win]
            ok = is_real & (vals_all[win] > -_BIG / 2)
            hot = ((jnp.arange(n_loc) == idxs_all[win])
                   & (me == win) & ok)                      # [n_loc]
            av = av - jnp.where(hot[:, None], d[None, :], 0.0)
            used = used | hot
            return (av, used), (best, ok)

        (_, _), (idx, ok) = jax.lax.scan(body, (avail, used0), demand)
        return idx[None, :], ok[None, :]

    return jax.jit(solve)


@functools.lru_cache(maxsize=16)
def _jit_sharded_apply_rows(n_pad: int, r_pad: int, k_pad: int,
                            n_shards: int):
    """Dirty-row scatter against the SHARDED device-resident avail
    matrix (GSPMD partitions the scatter; indices stay replicated)."""
    import jax

    sh = node_sharding(n_shards)
    rep = replicated_sharding(n_shards)

    def apply(avail_t, idx, rows):
        return avail_t.at[:, idx].set(rows.T)

    return jax.jit(apply, donate_argnums=(0,),
                   in_shardings=(sh, rep, rep), out_shardings=sh)


# ---------------------------------------------------------------------------
# Host wrappers (the BatchSolver / DeviceRuntimeSolver entry points).
# ---------------------------------------------------------------------------

def solve_matrices_sharded(avail: np.ndarray, total: np.ndarray,
                           demand: np.ndarray, counts: np.ndarray,
                           accel_node: np.ndarray,
                           accel_class: np.ndarray,
                           spread_threshold: float,
                           cost: Optional[np.ndarray],
                           invert_util: bool, zero_shifts: bool,
                           n_shards: int) -> np.ndarray:
    """Sharded one-tick waterfill; same contract as
    ``BatchSolver.solve_matrices`` (alloc [C, N] int64)."""
    import jax
    C, R = demand.shape
    N = avail.shape[0]
    c_pad, n_pad, r_pad = pads_sharded(C, N, R, n_shards)
    cost_p = np.zeros((c_pad, n_pad), np.float32) if cost is None \
        else _pad_to(cost.astype(np.float32), (c_pad, n_pad))
    shifts = np.zeros(c_pad, np.int32) if zero_shifts else \
        np.asarray((np.arange(c_pad) * _ROT_STRIDE) % n_pad, np.int32)
    fn = _jit_sharded_waterfill(c_pad, n_pad, r_pad, n_shards)
    allocs, _ = jax.block_until_ready(fn(
        _pad_to(avail.astype(np.float32), (n_pad, r_pad)),
        _pad_to(total.astype(np.float32), (n_pad, r_pad)),
        _pad_to(demand.astype(np.float32), (c_pad, r_pad)),
        _pad_to(counts.astype(np.float32), (c_pad,)),
        _pad_to(accel_node.astype(bool), (n_pad,)),
        _pad_to(accel_class.astype(bool), (c_pad,)),
        np.float32(spread_threshold), cost_p,
        np.float32(1.0 if invert_util else 0.0), shifts))
    allocs = np.asarray(jax.device_get(allocs))[:C, :N]
    return np.rint(allocs).astype(np.int64)


def solve_bundles_sharded(avail: np.ndarray, total: np.ndarray,
                          demand: np.ndarray, strategy: str,
                          excluded: Optional[np.ndarray],
                          n_shards: int):
    """Sharded bundle->node solve; same contract (and, for any N, the
    same bits) as ``BatchSolver.solve_bundles``."""
    import jax
    B, R = demand.shape
    N = avail.shape[0]
    b_pad = _round_up(max(B, 1), 8)
    n_pad = _round_up(max(N, 8), _GROUP * n_shards)
    r_pad = _round_up(max(R, 1), 8)
    if excluded is None:
        excluded = np.zeros(N, dtype=bool)
    pack_w = {"PACK": 10.0, "SPREAD": -10.0}.get(strategy, 0.0)
    fn = _jit_sharded_pack_bundles(b_pad, n_pad, r_pad, n_shards)
    idx, ok = jax.block_until_ready(fn(
        _pad_to(avail.astype(np.float32), (n_pad, r_pad)),
        _pad_to(total.astype(np.float32), (n_pad, r_pad)),
        _pad_to(demand.astype(np.float32), (b_pad, r_pad)),
        _pad_to(excluded.astype(bool), (n_pad,)),
        np.zeros(n_pad, dtype=bool),
        np.float32(pack_w),
        np.float32(1.0 if strategy == "STRICT_SPREAD" else 0.0)))
    idx = np.asarray(jax.device_get(idx))[0, :B].astype(np.int64)
    ok = np.asarray(jax.device_get(ok))[0, :B].astype(bool)
    return idx, ok


def solve_tick_sharded(avail_t, total_t, demand_dev, counts,
                       accel_node_dev, accel_dev, spread_threshold,
                       cost, c_cap: int, n_pad: int, r_pad: int,
                       nnz_max: int, n_shards: int) -> dict:
    """Sharded runtime tick against device-resident sharded world
    state; returns the merged sparse assignment (``merge_packed``)."""
    import jax
    fn = _jit_sharded_solve_tick(c_cap, n_pad, r_pad, nnz_max, n_shards)
    rows = np.asarray(jax.block_until_ready(fn(
        avail_t, total_t, demand_dev, counts, accel_node_dev, accel_dev,
        np.float32(spread_threshold), cost)))
    return merge_packed(rows, nnz_max)


def merge_packed(rows: np.ndarray, nnz_max: int) -> dict:
    """Merge per-shard packed rows [n_shards, 2*nnz_max+3] into one
    sparse assignment.  idx values are already GLOBAL flat positions;
    (placed, ok) are replicated; nnz sums across shards."""
    idx_parts, val_parts = [], []
    for row in rows:
        k = int(np.rint(row[2 * nnz_max + 2]))
        k = max(0, min(k, nnz_max))
        idx_parts.append(np.rint(row[:k]).astype(np.int64))
        val_parts.append(row[nnz_max:nnz_max + k])
    return {
        "idx": np.concatenate(idx_parts) if idx_parts
        else np.zeros(0, np.int64),
        "vals": np.concatenate(val_parts) if val_parts
        else np.zeros(0, np.float32),
        "placed": float(rows[0, 2 * nnz_max]),
        "ok": bool(rows[0, 2 * nnz_max + 1] > 0.5),
        "nnz": int(sum(int(np.rint(r[2 * nnz_max + 2])) for r in rows)),
    }
