"""Cluster introspection (GlobalState parity).

Parity: reference ``python/ray/state.py`` (``GlobalState`` — nodes, actors,
placement groups, jobs, cluster/available resources, timeline dump) backed
by the GCS tables instead of a GlobalStateAccessor.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu._private import worker as worker_mod


class GlobalState:
    def _gcs(self):
        w = worker_mod.global_worker()
        if not w.connected:
            raise RuntimeError("ray_tpu not initialized")
        return w.cluster.gcs

    def node_table(self) -> List[dict]:
        from ray_tpu._private.worker import nodes
        return nodes()

    def actor_table(self, actor_id=None) -> dict:
        gcs = self._gcs()
        info = gcs.actor_manager.all_actor_info()
        if actor_id is not None:
            return info.get(actor_id, {})
        return {aid.hex(): v for aid, v in info.items()}

    def task_table(self, task_id=None) -> dict:
        """Task lifecycle records from the task-event pipeline
        (reference ``GlobalState.task_table``), keyed by task id hex."""
        from ray_tpu.gcs.task_events import flushed_manager
        mgr = flushed_manager(self._gcs())
        if mgr is None:
            return {}
        if task_id is not None:
            tid = task_id.hex() if hasattr(task_id, "hex") else str(task_id)
            return mgr.get(tid) or {}
        return {rec["task_id"]: rec for rec in mgr.tasks()}

    def placement_group_table(self) -> dict:
        return self._gcs().placement_group_manager.table()

    def job_table(self) -> List[dict]:
        gcs = self._gcs()
        return [dict(v) for v in gcs.job_manager.jobs.values()]

    def cluster_resources(self) -> dict:
        return self._gcs().resource_manager.view.total_cluster_resources()

    def available_resources(self) -> dict:
        return self._gcs().resource_manager.live_available_resources()

    def chrome_tracing_dump(self, job: Optional[str] = None,
                            critical_path: bool = False) -> List[dict]:
        """Merged cluster timeline; ``job`` restricts the dump to one
        job's spans (``ray-tpu timeline --job``), ``critical_path``
        overlays that job's critical path as flow events."""
        w = worker_mod.global_worker()
        if w.connected and w.cluster is not None:
            from ray_tpu.gcs.timeline import merged_timeline
            return merged_timeline(w.cluster, job=job,
                                   critical_path=critical_path)
        from ray_tpu.util import tracing
        return tracing.chrome_tracing_dump()


state = GlobalState()
