"""Runtime context introspection.

Parity: reference ``python/ray/runtime_context.py`` — job/node/task/actor
ids, assigned resources, from driver or inside a task/actor.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private import worker_context


class RuntimeContext:
    @property
    def job_id(self):
        w = worker_mod.global_worker()
        return w.job_id

    def get_job_id(self) -> str:
        return self.job_id.hex()

    @property
    def node_id(self):
        ctx = worker_context.get_context()
        if ctx.node is not None:
            return ctx.node.node_id
        w = worker_mod.global_worker()
        return w.cluster.head_node.node_id if w.cluster else None

    def get_node_id(self) -> str:
        nid = self.node_id
        return nid.hex() if nid else ""

    @property
    def task_id(self):
        spec = worker_context.current_task_spec()
        return spec.task_id if spec else None

    def get_task_id(self) -> Optional[str]:
        t = self.task_id
        return t.hex() if t else None

    @property
    def actor_id(self):
        spec = worker_context.current_task_spec()
        return spec.actor_id if spec and spec.actor_id else None

    def get_actor_id(self) -> Optional[str]:
        a = self.actor_id
        return a.hex() if a else None

    @property
    def current_actor(self):
        """Handle to the current actor (inside an actor method)."""
        aid = self.actor_id
        if aid is None:
            raise RuntimeError("Not inside an actor method")
        from ray_tpu.actor import ActorHandle
        return ActorHandle(aid)

    @property
    def namespace(self) -> str:
        return worker_mod.global_worker().namespace

    @property
    def was_current_actor_reconstructed(self) -> bool:
        aid = self.actor_id
        if aid is None:
            return False
        w = worker_mod.global_worker()
        actor = w.cluster.gcs.actor_manager.get_actor(aid)
        return bool(actor and actor.num_restarts > 0)

    def get_assigned_resources(self) -> dict:
        spec = worker_context.current_task_spec()
        if spec is None:
            return {}
        # Default actors hold their lifetime resources (possibly none), not
        # the placement-only CPU used to schedule the creation task.
        if spec.is_actor_creation() and spec.lifetime_resources is not None:
            return spec.lifetime_resources.to_dict()
        return spec.resources.to_dict()

    def get_placement_group_id(self) -> Optional[str]:
        spec = worker_context.current_task_spec()
        if spec is None or spec.placement_group_id is None:
            return None
        return spec.placement_group_id.hex()


_context = RuntimeContext()


def get_runtime_context() -> RuntimeContext:
    return _context
