"""Checkpoint — the interchange object between trainers, tuners and
predictors.

Parity: reference ``python/ray/ml/checkpoint.py`` — one object
convertible between dict / directory / bytes representations, passed
across process boundaries by value.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional


class Checkpoint:
    def __init__(self, data: Dict[str, Any]):
        self._data = dict(data)

    # ---- constructors ---------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls(pickle.loads(blob))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        with open(os.path.join(path, "checkpoint.pkl"), "rb") as f:
            return cls.from_bytes(f.read())

    # ---- conversions ----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return dict(self._data)

    def to_bytes(self) -> bytes:
        return pickle.dumps(self._data, protocol=5)

    def to_directory(self, path: Optional[str] = None) -> str:
        import tempfile
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "checkpoint.pkl"), "wb") as f:
            f.write(self.to_bytes())
        return path

    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def __repr__(self):
        return f"Checkpoint(keys={sorted(self._data)})"
