"""Predictor + BatchPredictor: inference from a Checkpoint.

Parity: reference ``python/ray/ml/predictor.py`` +
``batch_predictor.py`` — a Predictor reconstructs a model (and its
preprocessor) from a Checkpoint and serves ``predict(batch)``;
BatchPredictor maps it over a Dataset with actor-pooled parallelism.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Type

from ray_tpu.ml.checkpoint import Checkpoint


class Predictor:
    """Subclass with ``from_checkpoint`` + ``_predict`` — or use the
    generic function flavor via ``Predictor.from_fn``."""

    def __init__(self, predict_fn: Callable, preprocessor=None):
        self._predict_fn = predict_fn
        self._preprocessor = preprocessor

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        model_from_checkpoint: Callable) -> "Predictor":
        """``model_from_checkpoint(checkpoint) -> predict_fn``; the
        checkpoint's stored preprocessor (if any) is applied first."""
        return cls(model_from_checkpoint(checkpoint),
                   preprocessor=checkpoint.get("_preprocessor"))

    def predict(self, batch: Dict):
        if self._preprocessor is not None:
            batch = self._preprocessor.transform_batch(batch)
        return self._predict_fn(batch)


class BatchPredictor:
    """Parallel inference over a Dataset (batch_predictor.py parity)."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor],
                 model_from_checkpoint: Callable):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._model_from_checkpoint = model_from_checkpoint

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        model_from_checkpoint: Callable,
                        predictor_cls: Type[Predictor] = Predictor
                        ) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, model_from_checkpoint)

    def predict(self, dataset, *, batch_size: Optional[int] = None):
        checkpoint = self._checkpoint
        predictor_cls = self._predictor_cls
        model_from_checkpoint = self._model_from_checkpoint
        state: Dict = {}

        def infer(batch):
            # One predictor per executing worker, built lazily from the
            # shipped checkpoint.
            predictor = state.get("p")
            if predictor is None:
                predictor = predictor_cls.from_checkpoint(
                    checkpoint, model_from_checkpoint)
                state["p"] = predictor
            return predictor.predict(batch)

        return dataset.map_batches(infer, batch_size=batch_size)
