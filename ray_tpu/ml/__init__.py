"""ray_tpu.ml — the AIR v0 unification layer.

Parity: reference ``python/ray/ml/`` (6.7k LoC preview): ``Preprocessor``
fit/transform over Datasets, ``Checkpoint`` as the interchange object,
``DataParallelTrainer.fit() -> Result``, ``Predictor``/``BatchPredictor``
for inference over Datasets, and a ``Tuner`` facade bridging trainers
into Tune.  Built purely on ray_tpu.train / ray_tpu.tune /
ray_tpu.data, like the reference builds only on its libraries.
"""

from ray_tpu.ml.checkpoint import Checkpoint
from ray_tpu.ml.predictor import BatchPredictor, Predictor
from ray_tpu.ml.preprocessor import (
    BatchMapper, Chain, MinMaxScaler, Preprocessor, StandardScaler)
from ray_tpu.ml.trainer import DataParallelTrainer, Result
from ray_tpu.ml.tuner import Tuner

__all__ = [
    "Checkpoint", "Predictor", "BatchPredictor", "Preprocessor",
    "StandardScaler", "MinMaxScaler", "BatchMapper", "Chain",
    "DataParallelTrainer", "Result", "Tuner",
]
