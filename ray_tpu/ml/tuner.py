"""Tuner: sweep a trainer's config through Tune.

Parity: reference ``python/ray/ml``'s Tune bridge (``Tuner.fit() ->
ResultGrid``-lite): the param_space overlays the trainer's
train_loop_config per trial; each trial runs the trainer's worker loop
and reports through the Tune session.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.ml.trainer import DataParallelTrainer, Result


class Tuner:
    def __init__(self, trainer: DataParallelTrainer, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 metric: str = "loss", mode: str = "min",
                 num_samples: int = 1, scheduler=None):
        self._trainer = trainer
        self._param_space = dict(param_space or {})
        self._metric = metric
        self._mode = mode
        self._num_samples = num_samples
        self._scheduler = scheduler

    def fit(self):
        from ray_tpu import tune

        base = self._trainer

        def trial(config):
            trainer = DataParallelTrainer(
                base._train_loop,
                train_loop_config={**base._config, **config},
                datasets=base._datasets,
                preprocessor=base._preprocessor,
                scaling_config={"num_workers": base._num_workers,
                                "use_tpu": base._use_tpu,
                                "resources_per_worker": base._resources})
            result = trainer.fit()
            metrics = dict(result.metrics)
            metrics.setdefault(self._metric, float("nan"))
            tune.report(**metrics)

        analysis = tune.run(trial, config=self._param_space,
                            metric=self._metric, mode=self._mode,
                            num_samples=self._num_samples,
                            scheduler=self._scheduler)
        return analysis


__all__ = ["Tuner", "Result"]
