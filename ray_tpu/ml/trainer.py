"""DataParallelTrainer + Result: the AIR training entry.

Parity: reference ``python/ray/ml/train/data_parallel_trainer.py`` —
``fit()`` runs a per-worker train loop (via ray_tpu.train's
Trainer/BackendExecutor) over preprocessed Datasets and returns a
``Result`` carrying final metrics + the last Checkpoint.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.ml.checkpoint import Checkpoint
from ray_tpu.ml.preprocessor import Preprocessor


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class DataParallelTrainer:
    """``fit()`` = preprocess datasets -> run train_loop_per_worker on a
    worker group -> collect metrics + final checkpoint."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 preprocessor: Optional[Preprocessor] = None,
                 scaling_config: Optional[Dict] = None):
        self._train_loop = train_loop_per_worker
        self._config = dict(train_loop_config or {})
        self._datasets = dict(datasets or {})
        self._preprocessor = preprocessor
        scaling = dict(scaling_config or {})
        self._num_workers = int(scaling.get("num_workers", 1))
        self._use_tpu = bool(scaling.get("use_tpu", False))
        self._resources = scaling.get("resources_per_worker")

    def fit(self) -> Result:
        from ray_tpu import train as train_mod

        datasets = dict(self._datasets)
        if self._preprocessor is not None and "train" in datasets:
            self._preprocessor.fit(datasets["train"])
            datasets = {k: self._preprocessor.transform(v)
                        for k, v in datasets.items()}

        # Per-worker shards ride the object store as materialized batch
        # lists (Datasets are driver-side handles).
        shard_batches = {
            name: list(ds.iter_batches(batch_format="numpy"))
            for name, ds in datasets.items()}
        config = dict(self._config)
        config["_ml_dataset_batches"] = shard_batches
        user_loop = self._train_loop

        def loop(cfg):
            return user_loop(cfg)

        trainer = train_mod.Trainer(
            backend="jax", num_workers=self._num_workers,
            use_tpu=self._use_tpu,
            resources_per_worker=self._resources)
        history: List[Dict[str, Any]] = []
        trainer.start()
        try:
            for reports in trainer.run_iterator(loop, config):
                if reports and reports[0]:
                    history.append(reports[0])
            last_ckpt = trainer.latest_checkpoint
        finally:
            trainer.shutdown()
        checkpoint = Checkpoint.from_dict(last_ckpt) \
            if isinstance(last_ckpt, dict) else None
        if self._preprocessor is not None and checkpoint is not None:
            data = checkpoint.to_dict()
            data["_preprocessor"] = self._preprocessor
            checkpoint = Checkpoint.from_dict(data)
        metrics = history[-1] if history else {}
        return Result(metrics=metrics, checkpoint=checkpoint,
                      metrics_history=history)


def get_dataset_batches(config: Dict, name: str = "train"):
    """Inside train_loop_per_worker: this worker's batches of the named
    dataset (rank-strided shard, session.get_dataset_shard parity)."""
    from ray_tpu.train import session
    batches = config.get("_ml_dataset_batches", {}).get(name, [])
    rank = session.world_rank()
    world = session.world_size()
    return batches[rank::world] if world > 1 else batches
