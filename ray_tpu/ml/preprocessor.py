"""Preprocessors: fit on a Dataset, transform Datasets and batches.

Parity: reference ``python/ray/ml/preprocessor.py`` +
``preprocessors/`` (StandardScaler, MinMaxScaler, BatchMapper, Chain):
``fit`` computes aggregate statistics with Dataset ops, ``transform``
maps blocks in parallel, ``transform_batch`` serves the same logic at
inference time.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, dataset) -> "Preprocessor":
        self._fit(dataset)
        self._fitted = True
        return self

    def transform(self, dataset):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return dataset.map_batches(self.transform_batch)

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict:
        raise NotImplementedError

    def _fit(self, dataset):
        pass

    def _needs_fit(self) -> bool:
        return True


class StandardScaler(Preprocessor):
    """(x - mean) / std per column."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats: Dict[str, tuple] = {}

    def _fit(self, dataset):
        for col in self.columns:
            mean = dataset.mean(col)
            std = dataset.std(col)
            self.stats[col] = (mean, std if std else 1.0)

    def transform_batch(self, batch):
        out = dict(batch)
        for col in self.columns:
            mean, std = self.stats[col]
            out[col] = (np.asarray(batch[col], dtype=np.float64) -
                        mean) / std
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats: Dict[str, tuple] = {}

    def _fit(self, dataset):
        for col in self.columns:
            lo = dataset.min(col)
            hi = dataset.max(col)
            self.stats[col] = (lo, (hi - lo) or 1.0)

    def transform_batch(self, batch):
        out = dict(batch)
        for col in self.columns:
            lo, span = self.stats[col]
            out[col] = (np.asarray(batch[col], dtype=np.float64) -
                        lo) / span
        return out


class BatchMapper(Preprocessor):
    """Stateless user-function preprocessor."""

    def __init__(self, fn: Callable[[Dict], Dict]):
        self.fn = fn

    def transform_batch(self, batch):
        return self.fn(batch)

    def _needs_fit(self) -> bool:
        return False


class Chain(Preprocessor):
    """Sequential composition; fit runs left to right, each stage
    fitting on the previous stage's output."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)

    def fit(self, dataset) -> "Chain":
        for stage in self.stages:
            dataset = stage.fit(dataset).transform(dataset)
        self._fitted = True
        return self

    def transform_batch(self, batch):
        for stage in self.stages:
            batch = stage.transform_batch(batch)
        return batch

    def _needs_fit(self) -> bool:
        return any(s._needs_fit() for s in self.stages)
