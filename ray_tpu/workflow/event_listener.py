"""Workflow events: durable external triggers.

Parity: reference ``python/ray/workflow/event_listener.py`` +
``api.py:364`` (``wait_for_event``): an :class:`EventListener` polls an
external source inside a workflow step; a second step commits the
checkpoint acknowledgment.  Because every step RESULT is checkpointed
by the execution engine, a workflow resumed after a crash past the
event step replays the recorded event instead of re-polling —
exactly-once consumption relative to the workflow's progress.

The reference's listeners are asyncio coroutines on an event fleet;
here they are plain callables on the step executor (the TPU runtime's
steps are sync tasks), with identical semantics.
"""

from __future__ import annotations

import time
from typing import Any

Event = Any


class EventListener:
    """Subclass and pass the TYPE to :func:`wait_for_event` (the
    listener is instantiated inside the polling step, on whatever node
    runs it)."""

    def poll_for_event(self, *args, **kwargs) -> Event:
        """Block until the event arrives; return its payload."""
        raise NotImplementedError

    def event_checkpointed(self, event: Event) -> None:
        """Called after the event is durably recorded in workflow
        storage — acknowledge/commit upstream (e.g. ack a queue
        message) here."""


class TimerListener(EventListener):
    """Fires once ``timestamp`` (unix seconds) has passed (reference
    ``TimerListener``)."""

    def poll_for_event(self, timestamp: float) -> Event:
        while time.time() < timestamp:
            time.sleep(min(0.1, max(0.0, timestamp - time.time())))
        return timestamp


def wait_for_event(event_listener_type, *args, **kwargs):
    """A step node resolving to the event payload (reference
    ``api.py:364``): poll step -> commit step, both checkpointed."""
    from ray_tpu.workflow import step

    if not (isinstance(event_listener_type, type) and
            issubclass(event_listener_type, EventListener)):
        raise TypeError(
            f"{event_listener_type!r} is not an EventListener subclass")

    @step
    def get_message(listener_type, *a, **kw) -> Event:
        return listener_type().poll_for_event(*a, **kw)

    @step
    def message_committed(listener_type, event: Event) -> Event:
        # Runs only after get_message's result is checkpointed — the
        # commit callback can safely ack the external source.
        listener_type().event_checkpointed(event)
        return event

    return message_committed.step(
        event_listener_type,
        get_message.step(event_listener_type, *args, **kwargs))
