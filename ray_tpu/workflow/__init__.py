"""ray_tpu.workflow — durable DAG execution on the task/actor core.

Parity: reference ``python/ray/workflow/`` — ``@workflow.step``
functions composed into DAGs, every step's inputs/outputs checkpointed
(``workflow_storage.py``), crash recovery that resumes from the durable
log instead of re-running finished work (``recovery.py``), and virtual
actors whose state survives process death
(``virtual_actor_class.py``).

    import ray_tpu
    from ray_tpu import workflow

    @workflow.step
    def fetch(url): ...

    @workflow.step
    def combine(a, b): ...

    result = combine.step(fetch.step(u1), fetch.step(u2)).run("my-wf")
    # ...crash anywhere; later:
    result = ray_tpu.get(workflow.resume("my-wf"))

Event primitives (``wait_for_event``/``sleep``) are not implemented.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.workflow.execution import (
    StepNode, VirtualActor, VirtualActorClass, resume_workflow)
from ray_tpu.workflow.storage import (
    WorkflowStatus, WorkflowStorage, default_base, list_workflows, set_base)

__all__ = [
    "init", "step", "virtual_actor", "get_actor", "resume", "resume_all",
    "get_output", "get_status", "list_all", "cancel", "delete",
    "WorkflowStatus", "EventListener", "TimerListener", "wait_for_event",
]


def __getattr__(name):
    # Late-bound: event_listener imports `step` from this module.
    if name in ("EventListener", "TimerListener", "wait_for_event"):
        from ray_tpu.workflow import event_listener
        return getattr(event_listener, name)
    raise AttributeError(name)


def init(storage: Optional[str] = None):
    """Point workflow storage at a directory (default:
    ``<temp_dir>/workflows``).  Reference: ``workflow.init(storage)``."""
    set_base(storage)


class _StepFunction:
    """What ``@workflow.step`` produces: call ``.step(*args)`` to build a
    DAG node, ``.options(...)`` to override per-step settings."""

    def __init__(self, fn, max_retries: int = 0, name: str = ""):
        self._fn = fn
        self._max_retries = max_retries
        self._name = name or getattr(fn, "__name__", "step")
        functools.update_wrapper(self, fn)

    def step(self, *args, **kwargs) -> StepNode:
        return StepNode(self._fn, args, kwargs, name=self._name,
                        max_retries=self._max_retries)

    def options(self, *, max_retries: Optional[int] = None,
                name: Optional[str] = None) -> "_StepFunction":
        return _StepFunction(
            self._fn,
            self._max_retries if max_retries is None else max_retries,
            self._name if name is None else name)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "workflow steps cannot be called directly; use "
            "`.step(*args)` to build the DAG, then `.run()`")


def step(*args, **kwargs):
    """``@workflow.step`` or ``@workflow.step(max_retries=3)``."""
    if len(args) == 1 and callable(args[0]) and not kwargs:
        return _StepFunction(args[0])

    def wrap(fn):
        return _StepFunction(fn, **kwargs)

    return wrap


class _VirtualActorDecorator:
    """``@workflow.virtual_actor`` + ``@workflow.virtual_actor.readonly``
    (readonly methods skip the state checkpoint)."""

    def __call__(self, cls: type) -> VirtualActorClass:
        return VirtualActorClass(cls)

    @staticmethod
    def readonly(method):
        method._workflow_readonly = True
        return method


virtual_actor = _VirtualActorDecorator()


def get_actor(actor_id: str) -> VirtualActor:
    storage = WorkflowStorage(actor_id)
    if not storage.has_actor(actor_id):
        raise ValueError(f"No virtual actor {actor_id!r} in storage")
    return VirtualActor(actor_id, storage)


def resume(workflow_id: str):
    """Resume a crashed/failed workflow; returns a ref on the result."""
    return resume_workflow(workflow_id)


def resume_all(include_failed: bool = True) -> Dict[str, Any]:
    """Resume every resumable workflow in storage; id -> result ref.
    Virtual-actor records (no step DAG) are skipped."""
    out = {}
    for wid, status in list_workflows().items():
        if status in (WorkflowStatus.RESUMABLE, WorkflowStatus.RUNNING) or \
                (include_failed and status == WorkflowStatus.FAILED):
            try:
                out[wid] = resume_workflow(wid)
            except ValueError:
                pass
    return out


@ray_tpu.remote
def _await_result(base: str, workflow_id: str):
    """Wait for a live run to reach a verdict, then read the checkpoint
    — never re-launches steps (a second launch would re-run in-flight
    side effects concurrently with the first)."""
    import time as _time

    storage = WorkflowStorage(workflow_id, base)
    while True:
        meta = storage.load_workflow() or {}
        status = meta.get("status")
        if status == WorkflowStatus.SUCCESSFUL:
            return storage.load_output(meta["entry_step"])
        if status in (WorkflowStatus.RESUMABLE, WorkflowStatus.FAILED,
                      WorkflowStatus.CANCELED):
            raise RuntimeError(
                f"workflow {workflow_id!r} ended as {status}; "
                "use workflow.resume() to re-run it")
        _time.sleep(0.1)


def get_output(workflow_id: str):
    """Ref on a workflow's final output.  Finished: served from the
    checkpoint.  Still running: a waiter tracks the live run (reference
    semantics — get_output never starts a second execution)."""
    storage = WorkflowStorage(workflow_id)
    meta = storage.load_workflow()
    if meta is None:
        raise ValueError(f"No workflow record for {workflow_id!r}")
    if not meta.get("entry_step"):
        raise ValueError(f"{workflow_id!r} is a virtual actor, not a "
                         "workflow")
    if meta.get("status") == WorkflowStatus.SUCCESSFUL and \
            storage.has_output(meta["entry_step"]):
        return ray_tpu.put(storage.load_output(meta["entry_step"]))
    if meta.get("status") in (WorkflowStatus.RESUMABLE,
                              WorkflowStatus.FAILED):
        return resume_workflow(workflow_id)
    return _await_result.remote(storage.base, workflow_id)


def get_status(workflow_id: str) -> Optional[str]:
    return WorkflowStorage(workflow_id).status()


def list_all(status_filter: Optional[str] = None) -> Dict[str, str]:
    all_wfs = list_workflows()
    if status_filter is None:
        return all_wfs
    return {k: v for k, v in all_wfs.items() if v == status_filter}


def cancel(workflow_id: str):
    """Best-effort cancel: mark CANCELED.  Steps not yet started observe
    the mark and refuse to run; resume() on a canceled workflow raises
    (running step bodies cannot be preempted)."""
    WorkflowStorage(workflow_id).set_status(WorkflowStatus.CANCELED)


def delete(workflow_id: str):
    WorkflowStorage(workflow_id).delete()
