"""Workflow execution: DAG build, checkpointed step tasks, recovery.

Parity: reference ``python/ray/workflow/step_executor.py`` (steps run as
tasks, outputs checkpointed before downstream consumption, continuation
steps — a step returning another step — recorded so recovery never
re-runs a finished step) and ``recovery.py`` (resume walks the durable
step log instead of user code).

Design: every step is persisted (function, args with ``StepRef``
placeholders, dep list) BEFORE execution, so the durable log alone can
finish the workflow after a crash.  Step execution itself is idempotent:
if the output checkpoint exists the step is skipped — which is the whole
recovery story.  Top-level DAG fan-out runs as parallel ``ray_tpu``
tasks ordered by upstream refs; continuations execute inline in the
parent step's task.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import cloudpickle as pickle  # locals-safe: steps/args may close over test-local classes
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.workflow.storage import (
    WorkflowStatus, WorkflowStorage, default_base)


class StepRef:
    """Placeholder for an upstream step's output inside persisted args."""

    __slots__ = ("step_id",)

    def __init__(self, step_id: str):
        self.step_id = step_id

    def __repr__(self):
        return f"StepRef({self.step_id})"


class StepNode:
    """One node of a workflow DAG (unexecuted).  Nodes are not mutated by
    execution, so one DAG object can be run under many workflow ids."""

    def __init__(self, fn, args: tuple, kwargs: dict, name: str = "",
                 max_retries: int = 0):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or getattr(fn, "__name__", "step")
        self.max_retries = max_retries

    # ---- public (reference Workflow.run / run_async) --------------------
    def run(self, workflow_id: Optional[str] = None) -> Any:
        return ray_tpu.get(self.run_async(workflow_id))

    def run_async(self, workflow_id: Optional[str] = None):
        workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
        storage = WorkflowStorage(workflow_id)
        entry_id = _persist_dag(storage, self)
        storage.save_workflow(entry_id, WorkflowStatus.RUNNING)
        return _launch(storage, entry_id, final=True)


def _collect_deps(obj, deps: List[str], ids: Dict[int, str]):
    """Recursively swap StepNodes for StepRefs in an args structure,
    collecting the dependency step ids (top-level containers only — a
    node hidden inside an arbitrary object is not discoverable)."""
    if isinstance(obj, StepNode):
        step_id = ids[id(obj)]
        deps.append(step_id)
        return StepRef(step_id)
    if isinstance(obj, list):
        return [_collect_deps(x, deps, ids) for x in obj]
    if isinstance(obj, tuple):
        return tuple(_collect_deps(x, deps, ids) for x in obj)
    if isinstance(obj, dict):
        return {k: _collect_deps(v, deps, ids) for k, v in obj.items()}
    return obj


def _persist_dag(storage: WorkflowStorage, entry: StepNode,
                 id_prefix: str = "") -> str:
    """Assign per-run step ids (postorder, name + counter) and write
    every step's function/args/deps to storage; returns the entry id.
    Ids live in a per-call map — nodes stay immutable so a DAG can be
    re-run under a different workflow id."""
    counter = itertools.count()
    ordered: List[StepNode] = []
    ids: Dict[int, str] = {}

    def visit(node: StepNode):
        if id(node) in ids:
            return
        ids[id(node)] = f"{id_prefix}{next(counter):04d}-{node.name}"
        for a in _iter_nodes(node.args) + _iter_nodes(node.kwargs):
            visit(a)
        ordered.append(node)

    visit(entry)
    for node in ordered:
        deps: List[str] = []
        swapped_args = _collect_deps(node.args, deps, ids)
        swapped_kwargs = _collect_deps(node.kwargs, deps, ids)
        blob = pickle.dumps((swapped_args, swapped_kwargs), protocol=5)
        storage.save_step(ids[id(node)], node.fn, blob, node.name,
                          sorted(set(deps)),
                          max_retries=node.max_retries)
    return ids[id(entry)]


def _iter_nodes(obj) -> List[StepNode]:
    out: List[StepNode] = []

    def walk(x):
        if isinstance(x, StepNode):
            out.append(x)
        elif isinstance(x, (list, tuple)):
            for y in x:
                walk(y)
        elif isinstance(x, dict):
            for y in x.values():
                walk(y)

    walk(obj)
    return out


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------

class WorkflowCanceledError(RuntimeError):
    pass


@ray_tpu.remote
def _step_task(base: str, workflow_id: str, step_id: str, final: bool,
               *_ordering_deps):
    """One checkpointed step as a framework task.  ``_ordering_deps`` are
    upstream step-task refs — consumed only for scheduling order; the
    actual values come from the durable output checkpoints."""
    storage = WorkflowStorage(workflow_id, base)
    if storage.status() == WorkflowStatus.CANCELED:
        raise WorkflowCanceledError(f"workflow {workflow_id!r} canceled")
    try:
        value = _run_step(storage, step_id)
    except Exception:
        if storage.status() != WorkflowStatus.CANCELED:
            storage.set_status(WorkflowStatus.RESUMABLE)
        raise
    if final:
        storage.set_status(WorkflowStatus.SUCCESSFUL)
    return value


def _launch(storage: WorkflowStorage, entry_step: str, final: bool):
    """Submit the DAG rooted at ``entry_step`` as parallel tasks in
    dependency order; returns the entry step's ref."""
    refs: Dict[str, Any] = {}

    def submit(step_id: str):
        if step_id in refs:
            return refs[step_id]
        meta = storage.step_meta(step_id) or {}
        dep_refs = [submit(d) for d in meta.get("deps", [])]
        refs[step_id] = _step_task.remote(
            storage.base, storage.workflow_id, step_id,
            final and step_id == entry_step, *dep_refs)
        return refs[step_id]

    return submit(entry_step)


def _resolve(storage: WorkflowStorage, obj):
    if isinstance(obj, StepRef):
        return _run_step(storage, obj.step_id)
    if isinstance(obj, list):
        return [_resolve(storage, x) for x in obj]
    if isinstance(obj, tuple):
        return tuple(_resolve(storage, x) for x in obj)
    if isinstance(obj, dict):
        return {k: _resolve(storage, v) for k, v in obj.items()}
    return obj


def _run_step(storage: WorkflowStorage, step_id: str) -> Any:
    """Idempotent recursive step execution from the durable log — THE
    recovery primitive.  Output checkpoint present -> done.  A recorded
    continuation is resumed instead of re-running the parent's body."""
    if storage.has_output(step_id):
        return storage.load_output(step_id)
    meta = storage.step_meta(step_id) or {}
    cont = meta.get("continuation")
    if cont is not None:
        value = _run_step(storage, cont)
        storage.save_output(step_id, value)
        return value
    fn = storage.load_step_fn(step_id)
    args, kwargs = pickle.loads(storage.load_step_args(step_id))
    args = _resolve(storage, args)
    kwargs = _resolve(storage, kwargs)
    storage.update_step_meta(step_id, state="RUNNING")
    retries = int(meta.get("max_retries", 0))
    attempt = 0
    while True:
        try:
            value = fn(*args, **kwargs)
            break
        except Exception:
            attempt += 1
            if attempt > retries:
                storage.update_step_meta(step_id, state="FAILED")
                raise
    if isinstance(value, StepNode):
        # Continuation: persist its sub-DAG under this step's namespace,
        # record the pointer BEFORE running it (so recovery resumes the
        # continuation instead of re-running this step's body), then
        # execute it inline.
        cont_id = _persist_dag(storage, value, id_prefix=f"{step_id}.")
        storage.update_step_meta(step_id, continuation=cont_id)
        value = _run_step(storage, cont_id)
    storage.save_output(step_id, value)
    return value


# ---------------------------------------------------------------------------
# Recovery
# ---------------------------------------------------------------------------

def resume_workflow(workflow_id: str, base: Optional[str] = None):
    """Resume a crashed/failed workflow from its durable log; returns a
    ref on the final result.  Finished steps are served from their
    checkpoints — only the missing suffix of the DAG re-executes."""
    storage = WorkflowStorage(workflow_id, base or default_base())
    meta = storage.load_workflow()
    if meta is None:
        raise ValueError(f"No workflow record for {workflow_id!r}")
    if not meta.get("entry_step"):
        raise ValueError(
            f"{workflow_id!r} is not a resumable workflow "
            "(virtual-actor records have no step DAG)")
    if meta.get("status") == WorkflowStatus.CANCELED:
        raise ValueError(f"workflow {workflow_id!r} was canceled")
    storage.set_status(WorkflowStatus.RUNNING)
    return _launch(storage, meta["entry_step"], final=True)


# ---------------------------------------------------------------------------
# Virtual actors (durable actors)
# ---------------------------------------------------------------------------

class VirtualActorClass:
    """Parity: reference ``virtual_actor_class.py`` — a class whose
    instances live in workflow storage: state is checkpointed after every
    non-readonly method, so the actor survives any process death."""

    def __init__(self, cls):
        self._cls = cls

    def get_or_create(self, actor_id: str, *args, **kwargs) -> "VirtualActor":
        storage = WorkflowStorage(actor_id)
        if not storage.has_actor(actor_id):
            instance = self._cls(*args, **kwargs)
            storage.save_actor_class(actor_id, self._cls)
            storage.save_actor_state(actor_id, _actor_state(instance), 0)
            storage.save_workflow("", "VIRTUAL_ACTOR")
        return VirtualActor(actor_id, storage)


class VirtualActor:
    """Handle on a durable actor; method calls run through
    ``handle.<method>.run(...)`` / ``.run_async(...)``."""

    def __init__(self, actor_id: str, storage: WorkflowStorage):
        self._actor_id = actor_id
        self._storage = storage
        self._cls = storage.load_actor_class(actor_id)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if not callable(getattr(self._cls, name, None)):
            raise AttributeError(
                f"virtual actor {self._actor_id!r} has no method {name!r}")
        return _VirtualMethod(self, name)

    @contextlib.contextmanager
    def _state_lock(self):
        """Cross-PROCESS mutual exclusion via flock: concurrent method
        calls may execute in different worker processes (process-mode
        pool), where an in-memory lock cannot serialize the
        read-modify-write on state.pkl."""
        import fcntl
        path = self._storage._actor_dir(self._actor_id)
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, ".lock"), "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lock_f, fcntl.LOCK_UN)

    def _call(self, method: str, args, kwargs, readonly: bool) -> Any:
        with self._state_lock():
            state, seq = self._storage.load_actor_state(self._actor_id)
            instance = object.__new__(self._cls)
            _restore_state(instance, state)
            result = getattr(instance, method)(*args, **kwargs)
            if not readonly:
                self._storage.save_actor_state(
                    self._actor_id, _actor_state(instance), seq + 1)
            return result


class _VirtualMethod:
    def __init__(self, actor: VirtualActor, name: str):
        self._actor = actor
        self._name = name
        self._readonly = getattr(
            getattr(actor._cls, name), "_workflow_readonly", False)

    def run(self, *args, **kwargs) -> Any:
        return self._actor._call(self._name, args, kwargs, self._readonly)

    def run_async(self, *args, **kwargs):
        @ray_tpu.remote
        def _invoke(actor_id, name, a, kw, ro, base):
            storage = WorkflowStorage(actor_id, base)
            return VirtualActor(actor_id, storage)._call(name, a, kw, ro)

        return _invoke.remote(self._actor._actor_id, self._name, args,
                              kwargs, self._readonly,
                              self._actor._storage.base)


def _actor_state(instance) -> Any:
    if hasattr(instance, "__getstate__"):
        return instance.__getstate__()
    return dict(instance.__dict__)


def _restore_state(instance, state):
    if hasattr(instance, "__setstate__"):
        instance.__setstate__(state)
    else:
        instance.__dict__.update(state)
