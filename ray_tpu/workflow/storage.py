"""Durable workflow storage: every step's function, inputs, and output
checkpointed to the filesystem.

Parity: reference ``python/ray/workflow/workflow_storage.py`` (step
input/output checkpoints keyed by workflow_id/step_id, workflow status
records, atomic writes) and ``workflow/storage/filesystem.py`` (the fs
backend: write-to-temp + rename for atomicity).

Layout::

    <base>/<workflow_id>/
        workflow.json                 # {entry_step, status}
        steps/<step_id>/
            fn.pkl                    # cloudpickled step function
            args.pkl                  # args with StepRef placeholders
            meta.json                 # {name, deps, state}
            output.pkl                # present iff the step finished
        actors/<actor_id>/
            class.pkl
            state.pkl                 # latest durable actor state
            seq                       # method-log sequence number
"""

from __future__ import annotations

import json
import os
import cloudpickle as pickle  # locals-safe: steps/args may close over test-local classes
import tempfile
import threading
from typing import Any, Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu._private.serialization import dumps_function, loads_function


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"
    CANCELED = "CANCELED"


def _atomic_write(path: str, data: bytes):
    """Write-then-rename so a crash never leaves a torn checkpoint
    (reference filesystem storage does exactly this)."""
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class WorkflowStorage:
    """One workflow's durable record."""

    def __init__(self, workflow_id: str, base: Optional[str] = None):
        self.workflow_id = workflow_id
        self.base = base or default_base()
        self.root = os.path.join(self.base, workflow_id)
        self._lock = threading.Lock()

    # ---- workflow-level record -----------------------------------------
    def save_workflow(self, entry_step: str, status: str):
        _atomic_write(
            os.path.join(self.root, "workflow.json"),
            json.dumps({"entry_step": entry_step,
                        "status": status}).encode())

    def load_workflow(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.root, "workflow.json"), "rb") as f:
                return json.loads(f.read())
        except (OSError, ValueError):
            return None

    def set_status(self, status: str):
        meta = self.load_workflow() or {"entry_step": ""}
        self.save_workflow(meta["entry_step"], status)

    def status(self) -> Optional[str]:
        meta = self.load_workflow()
        return None if meta is None else meta.get("status")

    # ---- step records ---------------------------------------------------
    def _step_dir(self, step_id: str) -> str:
        return os.path.join(self.root, "steps", step_id)

    def save_step(self, step_id: str, fn, args_blob: bytes, name: str,
                  deps: List[str], max_retries: int = 0):
        d = self._step_dir(step_id)
        _atomic_write(os.path.join(d, "fn.pkl"), dumps_function(fn))
        _atomic_write(os.path.join(d, "args.pkl"), args_blob)
        _atomic_write(os.path.join(d, "meta.json"), json.dumps({
            "name": name, "deps": deps, "state": "PENDING",
            "max_retries": max_retries}).encode())

    def step_meta(self, step_id: str) -> Optional[dict]:
        try:
            with open(os.path.join(self._step_dir(step_id),
                                   "meta.json"), "rb") as f:
                return json.loads(f.read())
        except (OSError, ValueError):
            return None

    def update_step_meta(self, step_id: str, **updates):
        with self._lock:
            meta = self.step_meta(step_id) or {}
            meta.update(updates)
            _atomic_write(os.path.join(self._step_dir(step_id), "meta.json"),
                          json.dumps(meta).encode())

    def load_step_fn(self, step_id: str):
        with open(os.path.join(self._step_dir(step_id), "fn.pkl"), "rb") as f:
            return loads_function(f.read())

    def load_step_args(self, step_id: str) -> bytes:
        with open(os.path.join(self._step_dir(step_id), "args.pkl"),
                  "rb") as f:
            return f.read()

    def save_output(self, step_id: str, value: Any):
        _atomic_write(os.path.join(self._step_dir(step_id), "output.pkl"),
                      pickle.dumps(value, protocol=5))
        self.update_step_meta(step_id, state="DONE")

    def has_output(self, step_id: str) -> bool:
        return os.path.exists(
            os.path.join(self._step_dir(step_id), "output.pkl"))

    def load_output(self, step_id: str) -> Any:
        with open(os.path.join(self._step_dir(step_id), "output.pkl"),
                  "rb") as f:
            return pickle.loads(f.read())

    def list_steps(self) -> List[str]:
        d = os.path.join(self.root, "steps")
        try:
            return sorted(os.listdir(d))
        except OSError:
            return []

    # ---- virtual-actor records -----------------------------------------
    def _actor_dir(self, actor_id: str) -> str:
        return os.path.join(self.root, "actors", actor_id)

    def save_actor_class(self, actor_id: str, cls):
        _atomic_write(os.path.join(self._actor_dir(actor_id), "class.pkl"),
                      dumps_function(cls))

    def load_actor_class(self, actor_id: str):
        with open(os.path.join(self._actor_dir(actor_id), "class.pkl"),
                  "rb") as f:
            return loads_function(f.read())

    def save_actor_state(self, actor_id: str, state: Any, seq: int):
        d = self._actor_dir(actor_id)
        _atomic_write(os.path.join(d, "state.pkl"),
                      pickle.dumps(state, protocol=5))
        _atomic_write(os.path.join(d, "seq"), str(seq).encode())

    def load_actor_state(self, actor_id: str):
        d = self._actor_dir(actor_id)
        with open(os.path.join(d, "state.pkl"), "rb") as f:
            state = pickle.loads(f.read())
        try:
            with open(os.path.join(d, "seq"), "rb") as f:
                seq = int(f.read())
        except (OSError, ValueError):
            seq = 0
        return state, seq

    def has_actor(self, actor_id: str) -> bool:
        return os.path.exists(
            os.path.join(self._actor_dir(actor_id), "state.pkl"))

    def delete(self):
        import shutil
        shutil.rmtree(self.root, ignore_errors=True)


_base_override: Optional[str] = None


def set_base(path: Optional[str]):
    global _base_override
    _base_override = path


def default_base() -> str:
    return _base_override or os.path.join(get_config().temp_dir, "workflows")


def list_workflows(base: Optional[str] = None) -> Dict[str, str]:
    """workflow_id -> status for every workflow in storage."""
    b = base or default_base()
    out: Dict[str, str] = {}
    try:
        ids = os.listdir(b)
    except OSError:
        return out
    for wid in ids:
        st = WorkflowStorage(wid, b).status()
        if st is not None:
            out[wid] = st
    return out
