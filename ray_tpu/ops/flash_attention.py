"""Pallas flash-attention kernel for a single TPU chip.

The hot op of the model stack: blockwise attention with running-max
softmax so the [L, L] score matrix never leaves VMEM.  MXU-aligned 128
blocks, f32 accumulation, bf16-friendly inputs.  (Pallas guide: grid +
BlockSpec pattern; preferred_element_type for MXU dots.)

Falls back to the jnp reference (ops.ring_attention.full_attention) on
non-TPU backends — the kernel itself is TPU-only.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, seq_len: int):
    # q_ref: [block_q, D]; k_ref/v_ref: [L, D]; o_ref: [block_q, D]
    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    q_blk = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    def body(i, carry):
        acc, m_i, l_i = carry
        k = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_blk * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_i - m_new)
        l_new = l_i * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    num_k = seq_len // block_k
    if causal:
        # Only blocks at or before this q block contribute.
        num_k_eff = jnp.minimum(num_k, (q_blk + 1) * block_q // block_k +
                                jnp.where(block_q % block_k, 1, 0))
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    upper = num_k_eff if causal else num_k
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128) -> jax.Array:
    """q, k, v: [B, L, H, D] -> [B, L, H, D].  L must be a multiple of
    the block sizes (pad upstream)."""
    B, L, H, D = q.shape
    scale = D ** -0.5
    # Collapse batch x heads into the leading grid dimension.
    qh = q.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    kh = k.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    vh = v.transpose(0, 2, 1, 3).reshape(B * H, L, D)

    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale, seq_len=L)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, L // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, L, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, L, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
    )(qh, kh, vh)
    return out.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True) -> jax.Array:
    """Backend dispatch: pallas kernel on TPU, jnp reference elsewhere."""
    from ray_tpu.ops.ring_attention import full_attention
    # Trace-time decision: backend is fixed per process ("axon" is the
    # tunneled TPU platform).
    platform = jax.default_backend()
    L = q.shape[1]
    if platform in ("tpu", "axon") and L % 128 == 0 and q.shape[-1] >= 64:
        return flash_attention(q, k, v, causal=causal)
    return full_attention(q, k, v, causal=causal)
