"""Ring attention — context parallelism over the ``sp`` mesh axis.

Long-context support the reference never had (SURVEY.md §5.7: "not
present...  the TPU substrate makes ring attention natural").  The sequence
is sharded across devices; each step every device computes a block of
attention between its local queries and the currently-held K/V chunk while
``jax.lax.ppermute`` rotates K/V around the ICI ring — compute and
communication overlap, and no device ever materializes the full [S, S]
score matrix.  Softmax is accumulated flash-style (running max + running
denominator), so the result is exact, not approximate.

Used inside ``shard_map`` with sequence dimension sharded over
``axis_name``.  Causality is handled per (q-chunk, kv-chunk) pair via the
global chunk indices.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, q_idx, kv_idx, chunk, causal, scale):
    """One q-chunk x kv-chunk block: returns (out_unnorm, row_max, row_sum).

    q: [B, Lq, H, D], k/v: [B, Lk, H, D].
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_idx * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (logits.shape[-2], logits.shape[-1]), 0)
        k_pos = kv_idx * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (logits.shape[-2], logits.shape[-1]), 1)
        mask = q_pos >= k_pos
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)                      # [B, H, Lq]
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l = jnp.sum(p, axis=-1)                           # [B, H, Lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention with sequence sharded over ``axis_name``.

    Shapes (per device): q, k, v: [B, L_local, H, D].  Must be called
    inside shard_map/pjit with ``axis_name`` a mesh axis; the global
    sequence is the concatenation of the per-device chunks in axis order.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    chunk = q.shape[1]
    if scale is None:
        scale = q.shape[-1] ** -0.5

    acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    acc = acc.transpose(0, 1, 2, 3)  # [B, Lq, H, D]
    run_max = jnp.full(q.shape[:1] + (q.shape[2], q.shape[1]), -jnp.inf,
                       jnp.float32)  # [B, H, Lq]
    run_sum = jnp.zeros_like(run_max)

    def step(carry, s):
        acc, run_max, run_sum, kk, vv = carry
        kv_idx = (my_idx - s) % n
        o, m, l = _block_attn(q, kk, vv, my_idx, kv_idx, chunk, causal,
                              scale)
        new_max = jnp.maximum(run_max, m)
        # Correction factors; guard fully-masked (-inf) rows.
        corr_old = jnp.exp(jnp.where(jnp.isfinite(run_max),
                                     run_max - new_max, -jnp.inf))
        corr_new = jnp.exp(jnp.where(jnp.isfinite(m), m - new_max, -jnp.inf))
        corr_old = jnp.where(jnp.isfinite(new_max), corr_old, 0.0)
        corr_new = jnp.where(jnp.isfinite(new_max), corr_new, 0.0)
        new_sum = run_sum * corr_old + l * corr_new
        # acc: [B, Lq, H, D]; corr: [B, H, Lq] -> [B, Lq, H, 1]
        acc = acc * corr_old.transpose(0, 2, 1)[..., None] + \
            o * corr_new.transpose(0, 2, 1)[..., None]
        # Rotate K/V to the next device on the ring (overlaps with the
        # next step's compute under XLA's latency-hiding scheduler).
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return (acc, new_max, new_sum, kk, vv), None

    (acc, run_max, run_sum, _, _), _ = jax.lax.scan(
        step, (acc, run_max, run_sum, k, v), jnp.arange(n))
    denom = jnp.maximum(run_sum, 1e-20).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Single-device reference attention ([B, L, H, D])."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        li, lj = logits.shape[-2], logits.shape[-1]
        mask = jax.lax.broadcasted_iota(jnp.int32, (li, lj), 0) >= \
            jax.lax.broadcasted_iota(jnp.int32, (li, lj), 1)
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
