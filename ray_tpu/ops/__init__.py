"""TPU compute ops: attention kernels, collectives-based primitives."""

from ray_tpu.ops.flash_attention import attention, flash_attention  # noqa: F401
from ray_tpu.ops.ring_attention import full_attention, ring_attention  # noqa: F401
