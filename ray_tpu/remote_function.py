"""@remote functions.

Parity: reference ``python/ray/remote_function.py`` — ``RemoteFunction``
wraps the user function; ``_remote`` (:246) pickles/exports the function
once, builds the task spec (inlining small args, promoting big ones), and
submits via the core worker (:421); ``.options(...)`` (:129) returns a
shallow override wrapper.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private import worker_context
from ray_tpu._private.executor import pack_args
from ray_tpu._private.task_spec import TaskType, make_spec

_DEFAULT_OPTIONS = dict(
    num_cpus=1, num_tpus=0, num_gpus=0, memory=0, resources=None,
    num_returns=1, max_retries=None, retry_exceptions=False,
    scheduling_strategy=None, runtime_env=None, name=None,
)


def _resource_dict(o: Dict[str, Any]) -> Dict[str, float]:
    res = dict(o.get("resources") or {})
    if o.get("num_cpus"):
        res["CPU"] = o["num_cpus"]
    if o.get("num_tpus"):
        res["TPU"] = o["num_tpus"]
    if o.get("num_gpus"):
        res["GPU"] = o["num_gpus"]
    if o.get("memory"):
        res["memory"] = o["memory"]
    return res


def resolve_pg_strategy(options: Dict[str, Any], resources: Dict[str, float]):
    """Rewrite resources for placement-group scheduling
    (bundle_spec.h formatted resources)."""
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy)
    strategy = options.get("scheduling_strategy")
    if not isinstance(strategy, PlacementGroupSchedulingStrategy):
        return resources, strategy, None, -1
    from ray_tpu.scheduler.bundle_packing import rewrite_resources_for_bundle
    pg = strategy.placement_group
    idx = strategy.placement_group_bundle_index
    rewritten = rewrite_resources_for_bundle(resources, pg.id, idx)
    return rewritten, "DEFAULT", pg.id, idx


def _normalized_env(runtime_env, w):
    """Package local paths + stamp the pool-keying hash at submit time
    (packaging.py parity — once per content, deduped in the GCS KV)."""
    if not runtime_env:
        return None
    from ray_tpu._private import runtime_env as runtime_env_mod
    return runtime_env_mod.normalize(runtime_env, w.cluster.gcs.kv)


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._function = fn
        self._name = f"{fn.__module__}.{fn.__qualname__}"
        self._options = dict(_DEFAULT_OPTIONS)
        self._options.update(options or {})
        self._function_id = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._name} cannot be called directly; use "
            f"{getattr(self._function, '__name__', 'f')}.remote().")

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        rf = RemoteFunction(self._function, merged)
        rf._function_id = self._function_id
        return rf

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, options):
        w = worker_mod.global_worker()
        if not w.connected:
            # Auto-init only from the main thread: a background thread
            # (actor-pool reaper, monitor timer) touching the API after
            # shutdown() must not silently boot a fresh cluster.
            import threading
            if threading.current_thread() is not threading.main_thread():
                raise RuntimeError(
                    "ray_tpu.init() has not been called yet (or the "
                    "cluster was shut down).")
            worker_mod.init()
        core = w.core_worker
        # Export every call: the FunctionManager dedupes per cluster, and a
        # RemoteFunction defined at module scope outlives init/shutdown
        # cycles (a cached id would dangle into the new cluster's empty KV).
        self._function_id = core.function_manager.export(self._function)
        resources = _resource_dict(options)
        resources, strategy, pg_id, bundle_idx = \
            resolve_pg_strategy(options, resources)
        flat = pack_args(args, kwargs)
        task_args, _, holders, borrowed = core.build_args(flat)
        parent = worker_context.current_task_spec()
        cfg_retries = options.get("max_retries")
        from ray_tpu._private.config import get_config
        spec = make_spec(
            job_id=w.job_id,
            owner_id=core.worker_id,
            function_id=self._function_id,
            function_name=options.get("name") or self._name,
            args=task_args,
            num_returns=options.get("num_returns", 1),
            resources=resources,
            scheduling_strategy=strategy,
            parent_task_id=parent.task_id if parent else core.driver_task_id,
            depth=(parent.depth + 1) if parent else 0,
            task_type=TaskType.NORMAL_TASK,
            max_retries=(cfg_retries if cfg_retries is not None
                         else get_config().task_max_retries),
            retry_exceptions=bool(options.get("retry_exceptions")),
            placement_group_id=pg_id,
            placement_group_bundle_index=bundle_idx,
            runtime_env=_normalized_env(options.get("runtime_env"), w),
            borrowed_ids=borrowed,
        )
        refs = core.submit_task(spec, holders=holders)
        if spec.num_returns == 0:
            return None
        if spec.num_returns == 1:
            return refs[0]
        return refs
