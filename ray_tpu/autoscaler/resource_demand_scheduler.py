"""ResourceDemandScheduler: demand vector -> node types to launch.

Parity: reference
``python/ray/autoscaler/_private/resource_demand_scheduler.py`` —
``get_nodes_to_launch`` (:143) runs (1) current-resource accounting,
(2) min_workers fill (:683 ``_add_min_workers_nodes``), (3) strict-spread
placement-group reservation (:580 ``reserve_and_allocate_spread``),
(4) first-fit-decreasing residual ``get_bin_pack_residual`` (:895), and
(5) ``get_nodes_for`` to pick node types for the residual, clamped by
``max_workers`` and ``upscaling_speed``.

TPU-first twist: instead of dict-of-dict first-fit loops, the packer is
columnar — demands dedup into (class, count) runs over a shared resource
vocabulary and each class is waterfilled against an [N, R] availability
matrix, the *same* math as ``ray_tpu.scheduler.jax_backend``'s device
solve.  ``get_bin_pack_residual`` and ``get_nodes_for`` ROUTE through
that kernel (pack mode: inverted-utilization ordering, zero per-class
shifts — most-utilized-feasible first, first-fit within a bucket) when
the problem is big enough for the device dispatch to pay
(``autoscaler_kernel_backend`` / ``autoscaler_kernel_min_cells``); the
numpy first-fit-decreasing below stays as the exact small-problem path
and the fallback on any kernel failure.  ``get_nodes_for`` batches each
candidate node type as a hypothetical fleet of ``max_to_add`` identical
nodes and solves ALL residual demand classes against it in one call —
the per-node python loop only survives on the numpy path.
"""

from __future__ import annotations

import copy
import importlib.util
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

ResourceDict = Dict[str, float]
NodeType = str

logger = logging.getLogger(__name__)

_JAX_OK = importlib.util.find_spec("jax") is not None

# Kernel-vs-numpy routing telemetry (folded into the autoscaler_solve
# bench row).
kernel_stats = {"kernel_solves": 0, "kernel_errors": 0, "numpy_solves": 0}


def _kernel_enabled(num_nodes: int, num_demands: int) -> bool:
    from ray_tpu._private.config import get_config
    cfg = get_config()
    mode = cfg.autoscaler_kernel_backend
    if mode == "off" or not _JAX_OK:
        return False
    if mode == "force":
        return True
    # A near-single-node pack is trivial host-side — the device
    # dispatch can never pay for it, however long the demand list.
    return num_nodes >= 8 and \
        num_nodes * num_demands >= cfg.autoscaler_kernel_min_cells


def _vocab(node_resources: List[ResourceDict],
           demands: List[ResourceDict]) -> List[str]:
    names: List[str] = []
    seen = set()
    for d in list(node_resources) + list(demands):
        for k in d:
            if k not in seen:
                seen.add(k)
                names.append(k)
    return names


def _to_matrix(dicts: List[ResourceDict], names: List[str]) -> np.ndarray:
    mat = np.zeros((len(dicts), len(names)), dtype=np.float64)
    idx = {n: i for i, n in enumerate(names)}
    for r, d in enumerate(dicts):
        for k, v in d.items():
            mat[r, idx[k]] = v
    return mat


def _sort_key(demand: ResourceDict):
    # Reference ordering (:929): more complex first, then heavier, then
    # lexicographic for stability.
    return (len(demand), sum(demand.values()), sorted(demand.items()))


def _group_sorted(demands: List[ResourceDict]):
    """Sorted (FFD order) then grouped into (demand, count) runs —
    identical consecutive demands waterfill identically to per-item FFD."""
    ordered = sorted(demands, key=_sort_key, reverse=True)
    runs: List[Tuple[ResourceDict, int]] = []
    for d in ordered:
        if runs and runs[-1][0] == d:
            runs[-1] = (d, runs[-1][1] + 1)
        else:
            runs.append((d, 1))
    return runs


def _pack_mode_matrices(node_resources: List[ResourceDict],
                        resource_demands: List[ResourceDict]):
    """Shared host-side prep for the pack-mode kernel solve."""
    names = _vocab(node_resources, resource_demands)
    runs = _group_sorted(resource_demands)
    demand = _to_matrix([d for d, _ in runs], names).astype(np.float32)
    counts = np.array([c for _, c in runs], dtype=np.float32)
    avail = _to_matrix(node_resources, names).astype(np.float32)
    return names, runs, demand, counts, avail


def _pack_mode_solve(runs, demand, counts, avail):
    """THE pack-mode kernel call (inverted utilization + zero shifts)
    — one implementation behind pack_with_jax_kernel AND the routed
    residual path.  Returns (unfulfilled, alloc[C, N])."""
    from ray_tpu.scheduler.jax_backend import BatchSolver
    alloc = BatchSolver().solve_matrices(
        avail, avail, demand, counts, spread_threshold=0.0,
        invert_util=True, zero_shifts=True)
    kernel_stats["kernel_solves"] += 1
    unfulfilled: List[ResourceDict] = []
    for i, (d, c) in enumerate(runs):
        short = int(c) - int(alloc[i].sum())
        if short > 0:
            unfulfilled.extend([dict(d)] * short)
    return unfulfilled, alloc


def _kernel_bin_pack(node_resources: List[ResourceDict],
                     resource_demands: List[ResourceDict],
                     ) -> Tuple[List[ResourceDict], List[ResourceDict], int]:
    """One-device-call bin-pack deriving the residual contract on top
    of :func:`_pack_mode_solve`: (unfulfilled, nodes_after,
    nodes_used)."""
    names, runs, demand, counts, avail = _pack_mode_matrices(
        node_resources, resource_demands)
    unfulfilled, alloc = _pack_mode_solve(runs, demand, counts, avail)
    after = np.maximum(
        avail.astype(np.float64) -
        alloc.T.astype(np.float64) @ demand.astype(np.float64), 0.0)
    idx = {n: i for i, n in enumerate(names)}
    nodes_after = [{k: float(after[r, idx[k]]) for k in orig}
                   for r, orig in enumerate(node_resources)]
    nodes_used = int((alloc.sum(axis=0) > 0).sum())
    return unfulfilled, nodes_after, nodes_used


def get_bin_pack_residual(node_resources: List[ResourceDict],
                          resource_demands: List[ResourceDict],
                          strict_spread: bool = False,
                          _use_kernel: Optional[bool] = None,
                          ) -> Tuple[List[ResourceDict], List[ResourceDict]]:
    """Columnar first-fit-decreasing. Returns (unfulfilled, nodes_after).

    Semantics match reference ``get_bin_pack_residual`` (:895): demands
    sorted complex/heavy-first; ``strict_spread`` forbids node reuse.
    Big non-strict problems route through the batched TPU kernel
    (``_kernel_bin_pack``); numpy is the exact small-problem path and
    the fallback on any kernel failure (``_use_kernel=False`` pins the
    numpy path — get_nodes_for's own fallback loop uses it so a
    just-failed kernel is not re-entered per inner call).
    """
    if not resource_demands:
        return [], copy.deepcopy(node_resources)
    use_kernel = _kernel_enabled(len(node_resources),
                                 len(resource_demands)) \
        if _use_kernel is None else _use_kernel
    if not strict_spread and use_kernel:
        try:
            unfulfilled, nodes_after, _ = _kernel_bin_pack(
                node_resources, resource_demands)
            return unfulfilled, nodes_after
        except Exception:
            kernel_stats["kernel_errors"] += 1
            logger.exception("autoscaler bin-pack kernel failed; "
                             "numpy fallback")
    kernel_stats["numpy_solves"] += 1
    names = _vocab(node_resources, resource_demands)
    avail = _to_matrix(node_resources, names)
    used = np.zeros(len(node_resources), dtype=bool)
    unfulfilled: List[ResourceDict] = []
    eps = 1e-9

    for demand, count in _group_sorted(resource_demands):
        d = _to_matrix([demand], names)[0]
        demanded = d > 0
        if not demanded.any():
            continue
        remaining = count
        if strict_spread:
            fits = (avail[:, demanded] + eps >= d[demanded]).all(axis=1)
            fits &= ~used
            for n in np.flatnonzero(fits)[:remaining]:
                avail[n] -= d
                used[n] = True
                remaining -= 1
        else:
            while remaining > 0:
                ratios = np.where(demanded[None, :],
                                  (avail + eps) / np.maximum(d, eps)[None, :],
                                  np.inf)
                cap = np.floor(ratios.min(axis=1)).astype(np.int64)
                if cap.max(initial=0) <= 0:
                    break
                # First-fit order: fill nodes in list order.
                for n in np.flatnonzero(cap > 0):
                    take = min(remaining, int(cap[n]))
                    avail[n] -= take * d
                    remaining -= take
                    if remaining == 0:
                        break
        unfulfilled.extend([dict(demand)] * remaining)

    idx = {n: i for i, n in enumerate(names)}
    nodes_after = []
    for r, orig in enumerate(node_resources):
        nodes_after.append({k: float(avail[r, idx[k]]) for k in orig})
    return unfulfilled, nodes_after


def _kernel_get_nodes_for(node_types: Dict[NodeType, dict],
                          existing_nodes: Dict[NodeType, int],
                          max_to_add: int,
                          resources: List[ResourceDict],
                          strict_spread: bool = False,
                          ) -> Tuple[Dict[NodeType, int],
                                     List[ResourceDict]]:
    """Batched node-count solve: each candidate type is a hypothetical
    fleet of ``headroom`` identical nodes and ALL residual demand
    classes solve against it in ONE kernel call (pack mode, so the
    solve uses as few fleet nodes as the fill allows); the used-node
    count IS the launch count for the winning type.  Replaces the
    numpy path's one-node-per-iteration python loop."""
    nodes_to_add: Dict[NodeType, int] = {}
    allocated = dict(existing_nodes)
    residual = list(resources)
    while residual and sum(nodes_to_add.values()) < max_to_add:
        budget = max_to_add - sum(nodes_to_add.values())
        best = None  # ((num_fit, -node_size), type, used, new_residual)
        for node_type, spec in node_types.items():
            limit = spec.get("max_workers", 2 ** 30)
            headroom = min(budget, limit - allocated.get(node_type, 0))
            if headroom <= 0:
                continue
            node_res = spec.get("resources", {})
            if not node_res:
                continue
            if strict_spread:
                # Each demand gets its own fresh node: a per-demand fit
                # check is exact (no packing interaction).  Place up to
                # ``headroom`` fitting demands, keep the rest.
                unfulfilled = []
                used = 0
                for d in residual:
                    if used < headroom and all(
                            node_res.get(k, 0) >= v
                            for k, v in d.items()):
                        used += 1
                    else:
                        unfulfilled.append(d)
            else:
                unfulfilled, _, used = _kernel_bin_pack(
                    [dict(node_res)] * headroom, residual)
            num_fit = len(residual) - len(unfulfilled)
            if num_fit <= 0:
                continue
            # Most demands fitted first, then FEWEST nodes launched,
            # then the smaller node type (less waste) — mirrors the
            # numpy path's one-node-at-a-time preference for the type
            # that fits the most demands per node.
            score = (num_fit, -max(used, 1), -sum(node_res.values()))
            if best is None or score > best[0]:
                best = (score, node_type, max(used, 1), unfulfilled)
        if best is None:
            break
        _, node_type, used, residual = best
        nodes_to_add[node_type] = nodes_to_add.get(node_type, 0) + used
        allocated[node_type] = allocated.get(node_type, 0) + used
    return nodes_to_add, residual


def get_nodes_for(node_types: Dict[NodeType, dict],
                  existing_nodes: Dict[NodeType, int],
                  max_to_add: int,
                  resources: List[ResourceDict],
                  strict_spread: bool = False,
                  ) -> Tuple[Dict[NodeType, int], List[ResourceDict]]:
    """Pick node types to satisfy ``resources`` (reference ``get_nodes_for``,
    :812): greedily add the node type whose resources satisfy the largest
    number of demands (utilization-scored), respecting per-type
    ``max_workers`` and the global ``max_to_add``.  Big problems route
    through the batched kernel variant; numpy below is the exact
    small-problem path and the fallback on any kernel failure."""
    if _kernel_enabled(max_to_add, len(resources)):
        try:
            return _kernel_get_nodes_for(node_types, existing_nodes,
                                         max_to_add, resources,
                                         strict_spread)
        except Exception:
            kernel_stats["kernel_errors"] += 1
            logger.exception("autoscaler get_nodes_for kernel failed; "
                             "numpy fallback")
    nodes_to_add: Dict[NodeType, int] = {}
    allocated = dict(existing_nodes)
    residual = list(resources)
    while residual and sum(nodes_to_add.values()) < max_to_add:
        best = None  # (score, node_type, new_residual)
        for node_type, spec in node_types.items():
            limit = spec.get("max_workers", 2 ** 30)
            if allocated.get(node_type, 0) >= limit:
                continue
            node_res = spec.get("resources", {})
            if not node_res:
                continue
            # Single-node pack: always the numpy path — never re-enter
            # a kernel this loop may be the fallback FOR.
            fulfilled, _ = get_bin_pack_residual(
                [dict(node_res)], residual, strict_spread=strict_spread,
                _use_kernel=False)
            num_fit = len(residual) - len(fulfilled)
            if num_fit <= 0:
                continue
            # Prefer the type that fits the most demands; tie-break on
            # fewer wasted resources (smaller node).
            score = (num_fit, -sum(node_res.values()))
            if best is None or score > best[0]:
                best = (score, node_type, fulfilled)
        if best is None:
            break
        _, node_type, residual = best
        nodes_to_add[node_type] = nodes_to_add.get(node_type, 0) + 1
        allocated[node_type] = allocated.get(node_type, 0) + 1
        if strict_spread:
            # Each strict-spread bundle got its own node; one node per pass.
            continue
    return nodes_to_add, residual


def _add_min_workers_nodes(node_resources: List[ResourceDict],
                           node_type_counts: Dict[NodeType, int],
                           node_types: Dict[NodeType, dict],
                           max_workers: int,
                           head_node_type: NodeType,
                           ensure_min_cluster_size: Optional[List[ResourceDict]],
                           ) -> Tuple[List[ResourceDict], Dict[NodeType, int],
                                      Dict[NodeType, int]]:
    """Fill per-type ``min_workers`` (reference :683)."""
    total_nodes_to_add: Dict[NodeType, int] = {}
    for node_type, spec in node_types.items():
        if node_type == head_node_type:
            continue
        target = min(spec.get("min_workers", 0),
                     spec.get("max_workers", 2 ** 30))
        have = node_type_counts.get(node_type, 0)
        if have < target:
            add = target - have
            total_nodes_to_add[node_type] = add
            node_type_counts[node_type] = target
            node_resources.extend(
                [dict(spec.get("resources", {}))] * add)
    # ensure_min_cluster_size: fit this demand against *static* cluster
    # shape, adding nodes if needed (request_resources()).
    if ensure_min_cluster_size:
        unfulfilled, _ = get_bin_pack_residual(
            node_resources, ensure_min_cluster_size)
        if unfulfilled:
            max_to_add = max_workers + 1 - sum(node_type_counts.values())
            extra, _ = get_nodes_for(node_types, node_type_counts,
                                     max_to_add, unfulfilled)
            for t, c in extra.items():
                total_nodes_to_add[t] = total_nodes_to_add.get(t, 0) + c
                node_type_counts[t] = node_type_counts.get(t, 0) + c
                node_resources.extend(
                    [dict(node_types[t].get("resources", {}))] * c)
    return node_resources, node_type_counts, total_nodes_to_add


def placement_groups_to_resource_demands(pending_placement_groups: List[dict]):
    """Flatten PG table data into plain demands + strict-spread bundle
    lists (reference :977). A pending PG dict: ``{"strategy": str,
    "bundles": [{resources...}, ...]}``."""
    resource_demand_vector: List[ResourceDict] = []
    unconverted: List[List[ResourceDict]] = []
    for pg in pending_placement_groups:
        strategy = pg.get("strategy", "PACK")
        bundles = [dict(b) for b in pg.get("bundles", []) if b]
        if strategy in ("PACK", "SPREAD"):
            # Soft constraints: treat as plain demands.
            resource_demand_vector.extend(bundles)
        elif strategy == "STRICT_PACK":
            # Must fit on one node: merge into a single demand.
            combined: ResourceDict = {}
            for b in bundles:
                for k, v in b.items():
                    combined[k] = combined.get(k, 0) + v
            if combined:
                resource_demand_vector.append(combined)
        elif strategy == "STRICT_SPREAD":
            unconverted.append(bundles)
    return resource_demand_vector, unconverted


class ResourceDemandScheduler:
    def __init__(self, node_types: Dict[NodeType, dict],
                 max_workers: int, head_node_type: NodeType = "head",
                 upscaling_speed: float = 1.0):
        self.node_types = copy.deepcopy(node_types)
        self.max_workers = max_workers
        self.head_node_type = head_node_type
        self.upscaling_speed = upscaling_speed

    def get_nodes_to_launch(
            self,
            node_type_counts: Dict[NodeType, int],
            launching_nodes: Dict[NodeType, int],
            resource_demands: List[ResourceDict],
            unused_resources_by_node: Dict[str, ResourceDict],
            pending_placement_groups: Optional[List[dict]] = None,
            node_type_by_node: Optional[Dict[str, NodeType]] = None,
            ensure_min_cluster_size: Optional[List[ResourceDict]] = None,
    ) -> Tuple[Dict[NodeType, int], List[ResourceDict]]:
        """Returns ({node_type: count_to_launch}, unfulfilled_demands)."""
        pending_placement_groups = pending_placement_groups or []
        # (1) Current usable resources: live nodes' *available* resources
        # plus full resources of nodes still launching.
        node_resources: List[ResourceDict] = \
            [dict(r) for r in unused_resources_by_node.values()]
        counts = dict(node_type_counts)
        for node_type, cnt in launching_nodes.items():
            counts[node_type] = counts.get(node_type, 0) + cnt
            node_resources.extend(
                [dict(self.node_types[node_type].get("resources", {}))] * cnt)

        # (2) min_workers fill.
        node_resources, counts, min_workers_to_add = _add_min_workers_nodes(
            node_resources, counts, self.node_types, self.max_workers,
            self.head_node_type, ensure_min_cluster_size)

        # (3) placement groups.
        pg_demands, strict_spreads = placement_groups_to_resource_demands(
            pending_placement_groups)
        demands = pg_demands + list(resource_demands)

        spread_to_add: Dict[NodeType, int] = {}
        for bundles in strict_spreads:
            # Reserve distinct nodes; launch for what doesn't fit.
            unfulfilled, node_resources = get_bin_pack_residual(
                node_resources, bundles, strict_spread=True)
            if unfulfilled:
                max_to_add = self.max_workers + 1 - sum(counts.values())
                to_add, _ = get_nodes_for(self.node_types, counts, max_to_add,
                                          unfulfilled, strict_spread=True)
                for t, c in to_add.items():
                    spread_to_add[t] = spread_to_add.get(t, 0) + c
                    counts[t] = counts.get(t, 0) + c

        # (4) residual demand after packing onto current+launching nodes.
        unfulfilled, _ = get_bin_pack_residual(node_resources, demands)

        # (5) node types for the residual.
        max_to_add = self.max_workers + 1 - sum(counts.values())
        demand_to_add, final_unfulfilled = get_nodes_for(
            self.node_types, counts, max_to_add, unfulfilled)

        total: Dict[NodeType, int] = {}
        for part in (min_workers_to_add, spread_to_add, demand_to_add):
            for t, c in part.items():
                total[t] = total.get(t, 0) + c
        total = self._apply_upscaling_limit(total, node_type_counts,
                                            launching_nodes)
        return total, final_unfulfilled

    def _apply_upscaling_limit(self, to_launch: Dict[NodeType, int],
                               existing: Dict[NodeType, int],
                               launching: Dict[NodeType, int]):
        """Clamp per-type launches to ``upscaling_speed * max(current, 5)``
        (reference ``_get_nodes_to_launch`` upscaling limit)."""
        limited: Dict[NodeType, int] = {}
        for t, c in to_launch.items():
            current = existing.get(t, 0) + launching.get(t, 0)
            limit = max(5, int(self.upscaling_speed * max(current, 1)))
            limited[t] = min(c, limit)
        return {t: c for t, c in limited.items() if c > 0}


def pack_with_jax_kernel(node_resources: List[ResourceDict],
                         resource_demands: List[ResourceDict]):
    """Batched variant: dedup demands into classes and solve all classes
    against all nodes in ONE TPU kernel call
    (``jax_backend.BatchSolver.solve_matrices`` in pack mode — the same
    solve ``get_bin_pack_residual`` now routes through by default).
    Kept for callers that want the raw alloc[C, N]; returns
    (unfulfilled, alloc)."""
    _, runs, demand, counts, avail = _pack_mode_matrices(
        node_resources, resource_demands)
    return _pack_mode_solve(runs, demand, counts, avail)
