"""Autoscaler SDK: programmatic resource requests.

Parity: reference ``python/ray/autoscaler/sdk.py`` —
``request_resources(num_cpus=..., bundles=[...])`` asks the autoscaler
to ensure the cluster can fit the given shape regardless of current
demand (flows into ``ensure_min_cluster_size``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

# The live monitor registers itself here on start (in-process cluster).
_active_monitor = None


def _set_active_monitor(monitor):
    global _active_monitor
    _active_monitor = monitor


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None):
    demands: List[Dict[str, float]] = []
    if num_cpus:
        demands.extend([{"CPU": 1}] * int(num_cpus))
    if bundles:
        demands.extend(dict(b) for b in bundles)
    if _active_monitor is None:
        raise RuntimeError("No autoscaler monitor is running; start one via "
                           "ray_tpu.autoscaler.Monitor(cluster, node_types)")
    _active_monitor.load_metrics.set_resource_requests(demands)
