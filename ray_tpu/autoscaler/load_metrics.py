"""LoadMetrics: the autoscaler's eventually-consistent view of cluster load.

Parity: reference ``python/ray/autoscaler/_private/load_metrics.py`` —
per-node static/available resource dicts keyed by ip, pending resource
demands from the scheduler, pending placement groups, explicit
``request_resources`` asks, and activity pruning for dead ips.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.debug.lock_order import diag_rlock


class LoadMetrics:
    def __init__(self):
        self.lock = diag_rlock("LoadMetrics.lock")
        self.last_heartbeat_by_ip: Dict[str, float] = {}
        self.static_resources_by_ip: Dict[str, Dict[str, float]] = {}
        self.dynamic_resources_by_ip: Dict[str, Dict[str, float]] = {}
        self.pending_demands: List[Dict[str, float]] = []
        self.pending_placement_groups: List[dict] = []
        self.resource_requests: List[Dict[str, float]] = []

    def update(self, ip: str, static_resources: Dict[str, float],
               dynamic_resources: Dict[str, float],
               pending_demands: Optional[List[Dict[str, float]]] = None,
               pending_placement_groups: Optional[List[dict]] = None):
        with self.lock:
            self.static_resources_by_ip[ip] = dict(static_resources)
            self.dynamic_resources_by_ip[ip] = dict(dynamic_resources)
            self.last_heartbeat_by_ip[ip] = time.time()
            if pending_demands is not None:
                self.pending_demands = list(pending_demands)
            if pending_placement_groups is not None:
                self.pending_placement_groups = list(pending_placement_groups)

    def mark_active(self, ip: str):
        with self.lock:
            self.last_heartbeat_by_ip[ip] = time.time()

    def is_active(self, ip: str) -> bool:
        with self.lock:
            return ip in self.last_heartbeat_by_ip

    def prune_active_ips(self, active_ips: List[str]):
        """Drop state for ips no longer in the cluster (reference
        ``LoadMetrics.prune_active_ips``)."""
        active = set(active_ips)
        with self.lock:
            for mapping in (self.last_heartbeat_by_ip,
                            self.static_resources_by_ip,
                            self.dynamic_resources_by_ip):
                for ip in list(mapping):
                    if ip not in active:
                        del mapping[ip]

    def get_node_resources(self) -> List[Dict[str, float]]:
        with self.lock:
            return list(self.static_resources_by_ip.values())

    def get_static_node_resources_by_ip(self) -> Dict[str, Dict[str, float]]:
        with self.lock:
            return dict(self.static_resources_by_ip)

    def get_resource_demand_vector(self, clip: bool = True,
                                   max_len: int = 1000):
        with self.lock:
            demands = list(self.pending_demands)
        return demands[:max_len] if clip else demands

    def get_pending_placement_groups(self) -> List[dict]:
        with self.lock:
            return list(self.pending_placement_groups)

    def set_resource_requests(self, requested: List[Dict[str, float]]):
        with self.lock:
            self.resource_requests = [dict(r) for r in requested if r]

    def get_resource_requests(self) -> List[Dict[str, float]]:
        with self.lock:
            return [dict(r) for r in self.resource_requests]

    def resources_avail_summary(self) -> str:
        with self.lock:
            total: Dict[str, float] = {}
            avail: Dict[str, float] = {}
            for res in self.static_resources_by_ip.values():
                for k, v in res.items():
                    total[k] = total.get(k, 0) + v
            for res in self.dynamic_resources_by_ip.values():
                for k, v in res.items():
                    avail[k] = avail.get(k, 0) + v
        return ", ".join(f"{avail.get(k, 0):g}/{total[k]:g} {k}"
                         for k in sorted(total))
