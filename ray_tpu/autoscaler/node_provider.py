"""Node providers: the pluggable "how do I get a machine" interface.

Parity: reference ``python/ray/autoscaler/node_provider.py`` (ABC with
``non_terminated_nodes/create_node/terminate_node/node_tags/...``) and
``python/ray/autoscaler/_private/fake_multi_node/node_provider.py``
(multi-node on one machine by launching extra in-process raylets with
distinct fake node IDs — the test substrate for autoscaler e2e runs).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

TAG_NODE_KIND = "node-kind"  # "head" | "worker"
TAG_NODE_TYPE = "user-node-type"
TAG_NODE_STATUS = "node-status"
STATUS_UP_TO_DATE = "up-to-date"
STATUS_UNINITIALIZED = "uninitialized"
NODE_KIND_HEAD = "head"
NODE_KIND_WORKER = "worker"


class NodeProvider:
    """Abstract provider. Node ids are provider-scoped strings."""

    def __init__(self, provider_config: Optional[dict] = None,
                 cluster_name: str = "default"):
        self.provider_config = provider_config or {}
        self.cluster_name = cluster_name

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def is_terminated(self, node_id: str) -> bool:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def internal_ip(self, node_id: str) -> str:
        raise NotImplementedError

    def create_node(self, node_config: dict, tags: Dict[str, str],
                    count: int) -> None:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def set_node_tags(self, node_id: str, tags: Dict[str, str]) -> None:
        raise NotImplementedError


class MockProvider(NodeProvider):
    """In-memory provider for unit tests (reference
    ``python/ray/tests/autoscaler_test_utils.py`` MockProvider)."""

    def __init__(self, provider_config=None, cluster_name="mock"):
        super().__init__(provider_config, cluster_name)
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._next = 0
        self.lock = threading.RLock()
        self.fail_creates = False

    def non_terminated_nodes(self, tag_filters=None):
        tag_filters = tag_filters or {}
        with self.lock:
            out = []
            for nid, n in self._nodes.items():
                if n["terminated"]:
                    continue
                if all(n["tags"].get(k) == v for k, v in tag_filters.items()):
                    out.append(nid)
            return out

    def is_running(self, node_id):
        with self.lock:
            return node_id in self._nodes and \
                not self._nodes[node_id]["terminated"]

    def is_terminated(self, node_id):
        with self.lock:
            n = self._nodes.get(node_id)
            return n is None or n["terminated"]

    def node_tags(self, node_id):
        with self.lock:
            return dict(self._nodes[node_id]["tags"])

    def internal_ip(self, node_id):
        return f"172.0.0.{int(node_id)}"

    def create_node(self, node_config, tags, count):
        if self.fail_creates:
            return
        with self.lock:
            for _ in range(count):
                nid = str(self._next)
                self._next += 1
                self._nodes[nid] = {"tags": dict(tags), "terminated": False,
                                    "config": dict(node_config or {}),
                                    "created_at": time.time()}

    def terminate_node(self, node_id):
        with self.lock:
            if node_id in self._nodes:
                self._nodes[node_id]["terminated"] = True

    def set_node_tags(self, node_id, tags):
        with self.lock:
            self._nodes[node_id]["tags"].update(tags)


class FakeMultiNodeProvider(NodeProvider):
    """Backs provider nodes with real in-process raylets on a
    :class:`ray_tpu._private.cluster.Cluster` — autoscaler decisions
    actually add/remove schedulable nodes, like the reference's
    fake_multi_node provider launches real raylet processes."""

    def __init__(self, cluster, node_types: Dict[str, dict],
                 cluster_name: str = "fake"):
        super().__init__({"node_types": node_types}, cluster_name)
        self.cluster = cluster
        self.node_types = node_types
        self._raylets: Dict[str, Any] = {}
        self._tags: Dict[str, Dict[str, str]] = {}
        self._terminated: set = set()
        self.lock = threading.RLock()
        # The pre-existing head node.
        head = cluster.head_node
        hid = head.node_id.hex()
        self._raylets[hid] = head
        self._tags[hid] = {TAG_NODE_KIND: NODE_KIND_HEAD,
                           TAG_NODE_TYPE: "head",
                           TAG_NODE_STATUS: STATUS_UP_TO_DATE}

    def non_terminated_nodes(self, tag_filters=None):
        tag_filters = tag_filters or {}
        with self.lock:
            return [nid for nid, tags in self._tags.items()
                    if nid not in self._terminated and
                    all(tags.get(k) == v for k, v in tag_filters.items())]

    def is_running(self, node_id):
        with self.lock:
            return node_id in self._raylets and node_id not in self._terminated

    def is_terminated(self, node_id):
        return not self.is_running(node_id)

    def node_tags(self, node_id):
        with self.lock:
            return dict(self._tags.get(node_id, {}))

    def internal_ip(self, node_id):
        return node_id[:12]

    def create_node(self, node_config, tags, count):
        node_type = tags.get(TAG_NODE_TYPE)
        resources = dict(
            (node_config or {}).get("resources") or
            self.node_types.get(node_type, {}).get("resources", {"CPU": 1}))
        with self.lock:
            for _ in range(count):
                raylet = self.cluster.add_node(
                    num_cpus=resources.get("CPU", 0),
                    num_tpus=resources.get("TPU", 0),
                    memory=resources.get("memory"),
                    resources={k: v for k, v in resources.items()
                               if k not in ("CPU", "TPU", "memory")},
                    object_store_memory=None)
                nid = raylet.node_id.hex()
                self._raylets[nid] = raylet
                self._tags[nid] = dict(tags)
                self._tags[nid][TAG_NODE_STATUS] = STATUS_UP_TO_DATE

    def terminate_node(self, node_id):
        with self.lock:
            raylet = self._raylets.get(node_id)
            if raylet is None or node_id in self._terminated:
                return
            self._terminated.add(node_id)
        self.cluster.remove_node(raylet)

    def set_node_tags(self, node_id, tags):
        with self.lock:
            self._tags[node_id].update(tags)


class LocalProcessProvider(NodeProvider):
    """Launches REAL worker-host OS processes (``node_host`` daemons)
    joined to the cluster's head service — the local analogue of the
    reference's node launcher flow (``node_launcher.py`` +
    ``updater.py``: provider creates the instance, the updater brings a
    raylet up on it; here create IS the bring-up, no SSH).  The
    autoscaler's decisions scale actual OS processes up and down."""

    def __init__(self, cluster, node_types: Dict[str, dict],
                 cluster_name: str = "local"):
        super().__init__({"node_types": node_types}, cluster_name)
        self.cluster = cluster
        self.node_types = node_types
        self._handles: Dict[str, Any] = {}   # node_id hex -> handle
        self._tags: Dict[str, Dict[str, str]] = {}
        self._terminated: set = set()
        self.lock = threading.RLock()
        head = cluster.head_node
        hid = head.node_id.hex()
        self._handles[hid] = None            # head is not ours to kill
        self._tags[hid] = {TAG_NODE_KIND: NODE_KIND_HEAD,
                           TAG_NODE_TYPE: "head",
                           TAG_NODE_STATUS: STATUS_UP_TO_DATE}

    def non_terminated_nodes(self, tag_filters=None):
        tag_filters = tag_filters or {}
        with self.lock:
            return [nid for nid, tags in self._tags.items()
                    if nid not in self._terminated and
                    all(tags.get(k) == v for k, v in tag_filters.items())]

    def is_running(self, node_id):
        with self.lock:
            if node_id in self._terminated or node_id not in self._tags:
                return False
            handle = self._handles.get(node_id)
        if handle is None:
            return True                      # head
        return handle.proc.poll() is None

    def is_terminated(self, node_id):
        return not self.is_running(node_id)

    def node_tags(self, node_id):
        with self.lock:
            return dict(self._tags.get(node_id, {}))

    def internal_ip(self, node_id):
        return node_id[:12]

    def create_node(self, node_config, tags, count,
                    timeout: float = 120.0,
                    spawn_interval_s: float = 0.0):
        node_type = tags.get(TAG_NODE_TYPE)
        resources = dict(
            (node_config or {}).get("resources") or
            self.node_types.get(node_type, {}).get("resources",
                                                   {"CPU": 1}))
        spec = dict(
            num_cpus=resources.get("CPU", 0),
            num_tpus=resources.get("TPU", 0),
            memory=resources.get("memory"),
            object_store_memory=(node_config or {}).get(
                "object_store_memory"),
            resources={k: v for k, v in resources.items()
                       if k not in ("CPU", "TPU", "memory")})
        # Spawn-all-then-wait-all: a 50–64-host fleet stands up in one
        # registration storm (the head's admission gate absorbs the
        # fan-in) instead of serial spawn×poll round trips.
        # ``spawn_interval_s`` optionally paces the Popen calls — on a
        # box with fewer cores than hosts, 50 interpreters booting at
        # once starve the head of the very CPU it needs to ANSWER the
        # registrations (boot-storm analogue of the worker-pool
        # startup stagger).
        handles = self.cluster.add_remote_nodes(
            [dict(spec) for _ in range(count)], timeout=timeout,
            spawn_interval_s=spawn_interval_s)
        for handle in handles:
            nid = handle.node_id.hex()
            with self.lock:
                self._handles[nid] = handle
                self._tags[nid] = dict(tags)
                self._tags[nid][TAG_NODE_STATUS] = STATUS_UP_TO_DATE
        return handles

    def terminate_node(self, node_id):
        with self.lock:
            handle = self._handles.get(node_id)
            if node_id in self._terminated:
                return
            self._terminated.add(node_id)
        if handle is not None:
            handle.terminate()

    def set_node_tags(self, node_id, tags):
        with self.lock:
            self._tags[node_id].update(tags)
