"""StandardAutoscaler: the update loop that launches/terminates nodes.

Parity: reference ``python/ray/autoscaler/_private/autoscaler.py``
(``StandardAutoscaler.update`` / ``_update``): each round —
(1) enumerate non-terminated worker nodes from the provider,
(2) terminate nodes idle longer than ``idle_timeout_minutes`` and nodes
beyond ``max_workers``, (3) ask the ResourceDemandScheduler what to
launch, (4) launch via the provider (reference uses NodeLauncher
threads; here launches are synchronous provider calls).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_WORKER, NodeProvider, TAG_NODE_KIND, TAG_NODE_STATUS,
    TAG_NODE_TYPE, STATUS_UP_TO_DATE)
from ray_tpu.autoscaler.resource_demand_scheduler import (
    ResourceDemandScheduler)

logger = logging.getLogger(__name__)


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider,
                 load_metrics: LoadMetrics,
                 node_types: Dict[str, dict],
                 max_workers: int = 10,
                 head_node_type: str = "head",
                 idle_timeout_minutes: float = 5.0,
                 upscaling_speed: float = 1.0):
        self.provider = provider
        self.load_metrics = load_metrics
        self.node_types = node_types
        self.max_workers = max_workers
        self.head_node_type = head_node_type
        self.idle_timeout_s = idle_timeout_minutes * 60.0
        self.resource_demand_scheduler = ResourceDemandScheduler(
            node_types, max_workers, head_node_type, upscaling_speed)
        # node_id -> time it was last seen busy.
        self.last_used_time_by_node: Dict[str, float] = {}
        self.num_launches = 0
        self.num_terminations = 0

    # ------------------------------------------------------------------
    def workers(self) -> List[str]:
        return self.provider.non_terminated_nodes(
            {TAG_NODE_KIND: NODE_KIND_WORKER})

    def _node_type_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for nid in self.provider.non_terminated_nodes({}):
            t = self.provider.node_tags(nid).get(TAG_NODE_TYPE)
            if t:
                counts[t] = counts.get(t, 0) + 1
        return counts

    def _is_idle(self, node_id: str, now: float) -> bool:
        ip = self.provider.internal_ip(node_id)
        static = self.load_metrics.static_resources_by_ip.get(ip)
        avail = self.load_metrics.dynamic_resources_by_ip.get(ip)
        if static is None or avail is None:
            return False  # no data yet — don't kill it
        busy = any(avail.get(k, 0) < v for k, v in static.items())
        if busy:
            self.last_used_time_by_node[node_id] = now
            return False
        last_used = self.last_used_time_by_node.setdefault(node_id, now)
        return (now - last_used) > self.idle_timeout_s

    # ------------------------------------------------------------------
    def update(self):
        now = time.time()
        workers = self.workers()

        # (2a) terminate over-the-cap workers (newest first).
        if len(workers) > self.max_workers:
            for nid in workers[self.max_workers:]:
                logger.info("Terminating %s: max_workers exceeded", nid)
                self.provider.terminate_node(nid)
                self.num_terminations += 1
            workers = self.workers()

        # (2b) terminate idle workers, respecting per-type min_workers.
        counts = self._node_type_counts()
        for nid in workers:
            node_type = self.provider.node_tags(nid).get(TAG_NODE_TYPE)
            min_w = self.node_types.get(node_type, {}).get("min_workers", 0)
            if counts.get(node_type, 0) <= min_w:
                continue
            if self._is_idle(nid, now):
                logger.info("Terminating %s: idle", nid)
                ip = self.provider.internal_ip(nid)
                self.provider.terminate_node(nid)
                counts[node_type] -= 1
                self.num_terminations += 1
                # Drop the dead node's resources immediately so this
                # round's bin-pack doesn't place demand on it.
                with self.load_metrics.lock:
                    self.load_metrics.static_resources_by_ip.pop(ip, None)
                    self.load_metrics.dynamic_resources_by_ip.pop(ip, None)

        # (3) what to launch.
        counts = self._node_type_counts()
        launching = self._pending_launches(counts)
        unused = dict(self.load_metrics.dynamic_resources_by_ip)
        to_launch, _ = self.resource_demand_scheduler.get_nodes_to_launch(
            counts, launching,
            self.load_metrics.get_resource_demand_vector(),
            unused,
            self.load_metrics.get_pending_placement_groups(),
            ensure_min_cluster_size=self.load_metrics.get_resource_requests())

        # (4) launch.
        for node_type, count in to_launch.items():
            logger.info("Launching %d x %s", count, node_type)
            self.provider.create_node(
                self.node_types[node_type],
                {TAG_NODE_KIND: NODE_KIND_WORKER,
                 TAG_NODE_TYPE: node_type,
                 TAG_NODE_STATUS: STATUS_UP_TO_DATE},
                count)
            self.num_launches += count
        return to_launch

    def _pending_launches(self, counts: Dict[str, int]) -> Dict[str, int]:
        # Synchronous providers have no in-flight launches; subclasses /
        # async providers can override.
        return {}

    def summary(self) -> dict:
        return {
            "workers": len(self.workers()),
            "node_type_counts": self._node_type_counts(),
            "launches": self.num_launches,
            "terminations": self.num_terminations,
            "resources": self.load_metrics.resources_avail_summary(),
        }
