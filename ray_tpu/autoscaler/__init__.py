"""Autoscaler: demand-driven cluster scaling.

Parity: reference ``python/ray/autoscaler/`` — ``StandardAutoscaler``
(`_private/autoscaler.py`), ``ResourceDemandScheduler``
(`_private/resource_demand_scheduler.py:48`), ``LoadMetrics``
(`_private/load_metrics.py`), ``NodeProvider`` plugin ABC
(`node_provider.py`) and the ``fake_multi_node`` provider used for
single-machine multi-node tests.

TPU-first twist: the bin-pack core is columnar ([D,R] demand matrix vs
[N,R] availability matrix over a shared resource vocabulary) and reuses
the same waterfill solve as the raylet's TPU scheduling kernel
(``ray_tpu.scheduler.jax_backend``) — one kernel signature serves the
raylet tick, GCS placement-group packing, and the autoscaler
(SURVEY.md section 3.4).
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.load_metrics import LoadMetrics  # noqa: F401
from ray_tpu.autoscaler.monitor import Monitor  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeMultiNodeProvider, NodeProvider)
from ray_tpu.autoscaler.resource_demand_scheduler import (  # noqa: F401
    ResourceDemandScheduler, get_bin_pack_residual)
from ray_tpu.autoscaler.sdk import request_resources  # noqa: F401
