"""Monitor: bridges the live cluster (GCS + raylets) to the autoscaler.

Parity: reference ``python/ray/autoscaler/_private/monitor.py`` — the
monitor process reads resource usage + demand from the GCS
(``update_load_metrics``) and runs ``StandardAutoscaler.update`` each
round. Here the monitor attaches to the in-process
:class:`ray_tpu._private.cluster.Cluster` and can run on an interval
thread or be ticked manually from tests (``update_all()``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider


class Monitor:
    def __init__(self, cluster, node_types: Dict[str, dict],
                 max_workers: int = 10,
                 idle_timeout_minutes: float = 5.0,
                 upscaling_speed: float = 1.0,
                 provider=None):
        self.cluster = cluster
        self.load_metrics = LoadMetrics()
        self.provider = provider or FakeMultiNodeProvider(cluster, node_types)
        self.autoscaler = StandardAutoscaler(
            self.provider, self.load_metrics, node_types,
            max_workers=max_workers,
            idle_timeout_minutes=idle_timeout_minutes,
            upscaling_speed=upscaling_speed)
        self._timer: Optional[threading.Timer] = None
        self._stopped = False
        from ray_tpu.autoscaler import sdk
        sdk._set_active_monitor(self)

    # ------------------------------------------------------------------
    def update_load_metrics(self):
        """Pull resource reports + pending demand from every raylet and
        pending PGs from the GCS (reference Monitor.update_load_metrics)."""
        demands = []
        gcs = self.cluster.gcs
        for raylet in list(gcs.raylets().values()):
            report = raylet.get_resource_report()
            ip = raylet.node_id.hex()[:12]
            demands.extend(raylet.cluster_task_manager.resource_load())
            self.load_metrics.update(ip, report["total"], report["available"])
        pending_pgs = []
        pgm = getattr(gcs, "placement_group_manager", None)
        if pgm is not None:
            for pg_id in list(getattr(pgm, "_pending", [])):
                pg = pgm.get(pg_id)
                if pg is not None:
                    pending_pgs.append({
                        "strategy": pg.strategy,
                        "bundles": [b.to_dict() for b in pg.bundles]})
        with self.load_metrics.lock:
            self.load_metrics.pending_demands = demands
            self.load_metrics.pending_placement_groups = pending_pgs
        alive = [r.node_id.hex()[:12] for r in gcs.raylets().values()]
        self.load_metrics.prune_active_ips(alive)

    def update_all(self):
        """One full monitor round: refresh metrics, run the autoscaler."""
        self.update_load_metrics()
        return self.autoscaler.update()

    # ------------------------------------------------------------------
    def start(self, interval_s: float = 5.0):
        def tick():
            if self._stopped:
                return
            try:
                self.update_all()
            finally:
                if not self._stopped:
                    self._timer = threading.Timer(interval_s, tick)
                    self._timer.daemon = True
                    self._timer.start()
        self._timer = threading.Timer(interval_s, tick)
        self._timer.daemon = True
        self._timer.start()

    def stop(self):
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
