"""ActorPool — schedule work over a fixed pool of actors.

Parity: reference ``python/ray/util/actor_pool.py`` (``ActorPool.submit``,
``get_next``, ``get_next_unordered``, ``map``, ``map_unordered``,
``has_next``, ``has_free``, ``push``, ``pop_idle``).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    """Operate on a fixed pool of actors, distributing tasks to free ones.

    >>> @ray_tpu.remote
    ... class W:
    ...     def double(self, v): return 2 * v
    >>> pool = ActorPool([W.remote(), W.remote()])
    >>> list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    [2, 4, 6, 8]
    """

    def __init__(self, actors: Iterable[Any]):
        self._idle_actors: List[Any] = list(actors)
        # ref -> actor for in-flight work, plus submission-order indexing.
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    # ---- submission -----------------------------------------------------
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Apply ``fn(actor, value)`` on an idle actor (queues if none)."""
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def has_free(self) -> bool:
        return bool(self._idle_actors) and not self._pending_submits

    # ---- retrieval ------------------------------------------------------
    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        if self._next_return_index >= self._next_task_index or \
                self._next_return_index not in self._index_to_future:
            raise ValueError("It is not allowed to call get_next() after "
                             "get_next_unordered()")
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        result = ray_tpu.get(future, timeout=timeout)
        self._return_actor(self._future_to_actor.pop(future))
        return result

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next result to become ready, regardless of submission order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        future = ready[0]
        for i, f in list(self._index_to_future.items()):
            if f is future or f == future:
                del self._index_to_future[i]
                break
        result = ray_tpu.get(future)
        self._return_actor(self._future_to_actor.pop(future))
        return result

    def _return_actor(self, actor) -> None:
        self._idle_actors.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    # ---- bulk maps ------------------------------------------------------
    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ---- pool management ------------------------------------------------
    def push(self, actor) -> None:
        """Add an idle actor to the pool."""
        busy = set(self._future_to_actor.values())
        if actor in self._idle_actors or actor in busy:
            raise ValueError("Actor already belongs to current ActorPool")
        self._return_actor(actor)

    def pop_idle(self):
        """Remove and return an idle actor, or None if none are idle."""
        if self.has_free():
            return self._idle_actors.pop()
        return None
