"""ActorPool — fan work out over a fixed set of actors.

Same public surface as the reference's ``python/ray/util/actor_pool.py``
(``submit`` / ``get_next`` / ``get_next_unordered`` / ``map`` /
``map_unordered`` / ``has_next`` / ``has_free`` / ``push`` /
``pop_idle``), re-implemented around a ticketed in-flight table: every
submission takes a monotonically increasing ticket, ordered retrieval
walks the ticket sequence, unordered retrieval races the in-flight refs
with ``wait``.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions
from ray_tpu._private.object_ref import ObjectRef


class ActorPool:
    """Distribute ``fn(actor, value)`` calls across idle pool actors.

    >>> @ray_tpu.remote
    ... class W:
    ...     def double(self, v): return 2 * v
    >>> pool = ActorPool([W.remote(), W.remote()])
    >>> list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    [2, 4, 6, 8]
    """

    def __init__(self, actors: Iterable[Any]):
        self._free: List[Any] = list(actors)
        self._backlog: deque = deque()          # (fn, value) with no actor
        self._inflight: Dict[ObjectRef, Tuple[int, Any]] = {}
        self._ticket_refs: Dict[int, ObjectRef] = {}
        self._ticket_seq = 0                    # next ticket to hand out
        self._emit_cursor = 0                   # next ticket get_next emits

    # ---- submission -----------------------------------------------------
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Launch ``fn(actor, value)`` on a free actor, or queue it."""
        if not self._free:
            self._backlog.append((fn, value))
            return
        actor = self._free.pop()
        ref = fn(actor, value)
        self._inflight[ref] = (self._ticket_seq, actor)
        self._ticket_refs[self._ticket_seq] = ref
        self._ticket_seq += 1

    def has_next(self) -> bool:
        return bool(self._inflight) or bool(self._backlog)

    def has_free(self) -> bool:
        return bool(self._free) and not self._backlog

    # ---- retrieval ------------------------------------------------------
    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Result of the oldest outstanding submission (ticket order).

        A timeout leaves the pool untouched (the submission stays
        retrievable); a task error recycles the actor before re-raising,
        so one failed task never wedges the pool."""
        if not self.has_next():
            raise StopIteration("ActorPool has no outstanding work")
        ref = self._ticket_refs.get(self._emit_cursor)
        if ref is None:
            raise ValueError(
                "ordered get_next() cannot follow get_next_unordered(): "
                "the ticket sequence has a hole")
        try:
            result = ray_tpu.get(ref, timeout=timeout)
        except exceptions.GetTimeoutError:
            raise            # nothing consumed; caller may retry
        except Exception:
            self._consume(self._emit_cursor, ref)
            raise
        self._consume(self._emit_cursor, ref)
        return result

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Whichever outstanding result lands first."""
        if not self.has_next():
            raise StopIteration("ActorPool has no outstanding work")
        done, _rest = ray_tpu.wait(list(self._inflight), num_returns=1,
                                   timeout=timeout)
        if not done:
            raise TimeoutError(
                f"no ActorPool result became ready within {timeout}s")
        ref = done[0]
        ticket, _actor = self._inflight[ref]
        try:
            return ray_tpu.get(ref)
        finally:
            self._consume(ticket, ref)

    def _consume(self, ticket: int, ref: ObjectRef) -> None:
        """Retire a finished submission: drop its ticket, advance the
        ordered cursor past it, and recycle its actor."""
        self._ticket_refs.pop(ticket, None)
        if ticket == self._emit_cursor:
            self._emit_cursor += 1
        self._recycle(ref)

    def _recycle(self, ref: ObjectRef) -> None:
        """Free the actor behind a finished ref and drain the backlog."""
        _ticket, actor = self._inflight.pop(ref)
        self._free.append(actor)
        if self._backlog:
            self.submit(*self._backlog.popleft())

    # ---- bulk maps ------------------------------------------------------
    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ---- pool membership ------------------------------------------------
    def push(self, actor) -> None:
        """Grow the pool with one more (idle) actor."""
        if actor in self._free or \
                any(actor is a for _t, a in self._inflight.values()):
            raise ValueError("actor is already a member of this ActorPool")
        self._free.append(actor)
        if self._backlog:
            self.submit(*self._backlog.popleft())

    def pop_idle(self):
        """Detach one idle actor from the pool (None if all are busy)."""
        if self.has_free():
            return self._free.pop()
        return None
