"""User-facing scheduling strategies.

Parity: reference ``python/ray/util/scheduling_strategies.py`` —
"DEFAULT"/"SPREAD" strings, PlacementGroupSchedulingStrategy,
NodeAffinitySchedulingStrategy.
"""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: Optional[bool] = None):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = \
            placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id, soft: bool = False):
        # Accept hex string or NodeID.
        self.node_id = node_id
        self.soft = soft


SchedulingStrategyT = object  # "DEFAULT" | "SPREAD" | strategy instance
