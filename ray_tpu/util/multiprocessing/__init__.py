"""``multiprocessing.Pool`` adapter backed by framework actors.

Parity: reference ``python/ray/util/multiprocessing/`` — a drop-in
``Pool`` whose "processes" are actors, so existing multiprocessing code
scales across the cluster unchanged:

    from ray_tpu.util.multiprocessing import Pool
    with Pool(4) as pool:
        squares = pool.map(square, range(100))
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


@ray_tpu.remote
class _PoolWorker:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))

    def run_batch(self, fn, chunk):
        return [fn(*args) for args in chunk]


class AsyncResult:
    """``multiprocessing.pool.AsyncResult`` surface over an ObjectRef."""

    def __init__(self, ref, unpack: Optional[Callable] = None):
        self._ref = ref
        self._unpack = unpack

    def get(self, timeout: Optional[float] = None) -> Any:
        value = ray_tpu.get(self._ref, timeout=timeout)
        return self._unpack(value) if self._unpack else value

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait([self._ref], num_returns=1, timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait([self._ref], num_returns=1, timeout=0)
        return bool(ready)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if processes is None:
            import os
            processes = os.cpu_count() or 1
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._actors = [_PoolWorker.remote(initializer, initargs)
                        for _ in range(processes)]
        self._pool = ActorPool(list(self._actors))
        self._rr = itertools.cycle(self._actors)
        self._closed = False

    # ---- apply ---------------------------------------------------------
    def apply(self, fn: Callable, args: tuple = (),
              kwds: Optional[dict] = None) -> Any:
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        self._check_open()
        actor = next(self._rr)
        return AsyncResult(actor.run.remote(fn, args, kwds))

    # ---- map -----------------------------------------------------------
    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = [(x,) for x in iterable]
        return self._star_chunks(items, chunksize)

    def _star_chunks(self, items: List[tuple],
                     chunksize: Optional[int]) -> List[List[tuple]]:
        if chunksize is None:
            chunksize = max(1, len(items) // (4 * len(self._actors)) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        chunks = self._chunks(iterable, chunksize)
        refs = [next(self._rr).run_batch.remote(fn, c) for c in chunks]

        @ray_tpu.remote
        def _gather(*batches):
            return [v for b in batches for v in b]

        return AsyncResult(_gather.remote(*refs))

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List:
        self._check_open()
        chunks = self._star_chunks(list(iterable), chunksize)
        refs = [next(self._rr).run_batch.remote(fn, c) for c in chunks]
        return [v for b in ray_tpu.get(refs) for v in b]

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        self._check_open()
        for chunk in self._chunks(iterable, chunksize):
            for v in ray_tpu.get(
                    next(self._rr).run_batch.remote(fn, chunk)):
                yield v

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        self._check_open()
        chunks = self._chunks(iterable, chunksize)
        for chunk in chunks:
            self._pool.submit(
                lambda actor, c: actor.run_batch.remote(fn, c), chunk)
        while self._pool.has_next():
            for v in self._pool.get_next_unordered():
                yield v

    # ---- lifecycle -----------------------------------------------------
    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for actor in self._actors:
            ray_tpu.kill(actor)
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool.join() requires close() first")
        self._actors = []

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        self.terminate()
