"""User-facing metrics API: Counter, Gauge, Histogram.

Parity: reference ``python/ray/util/metrics.py`` — user metrics flow
through the same per-node agent as internal stats and are exported to
Prometheus.  Here they land in the process-wide
:mod:`ray_tpu._private.metrics_agent` registry, rendered by the
dashboard's ``/metrics`` route.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Union

from ray_tpu._private.metrics_agent import get_metrics_registry


class Metric:
    """Base class; holds name, description and default tag values."""

    _type = "gauge"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Union[Tuple[str, ...], List[str]]] = None):
        if not name:
            raise ValueError("name must not be empty")
        tag_keys = tuple(tag_keys or ())
        for k in tag_keys:
            if not isinstance(k, str):
                raise TypeError("tag_keys must be strings")
        self._name = name
        self._description = description
        self._tag_keys = tag_keys
        self._default_tags: Dict[str, str] = {}
        get_metrics_registry().register(
            name, self._type, description,
            buckets=getattr(self, "_boundaries", None))

    @property
    def info(self) -> Dict:
        return {
            "name": self._name,
            "description": self._description,
            "tag_keys": self._tag_keys,
            "default_tags": dict(self._default_tags),
        }

    def set_default_tags(self, default_tags: Dict[str, str]) -> "Metric":
        for k in default_tags:
            if k not in self._tag_keys:
                raise ValueError(f"Unrecognized tag key {k!r}")
        self._default_tags = dict(default_tags)
        return self

    def _label_key(self, tags: Optional[Dict[str, str]]):
        merged = dict(self._default_tags)
        if tags:
            for k in tags:
                if k not in self._tag_keys:
                    raise ValueError(f"Unrecognized tag key {k!r}")
            merged.update(tags)
        missing = set(self._tag_keys) - set(merged)
        if missing:
            raise ValueError(f"Missing value for tag key(s): {sorted(missing)}")
        return tuple(sorted(merged.items()))


class Counter(Metric):
    """A cumulative metric that only increases."""

    _type = "counter"

    def inc(self, value: Union[int, float] = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value <= 0:
            raise ValueError("value must be positive")
        get_metrics_registry().inc(self._name, float(value),
                                   self._label_key(tags))


class Gauge(Metric):
    """A point-in-time value that can go up and down."""

    def set(self, value: Union[int, float],
            tags: Optional[Dict[str, str]] = None) -> None:
        get_metrics_registry().set(self._name, float(value),
                                   self._label_key(tags))


class Histogram(Metric):
    """Observations bucketed into configurable boundaries."""

    _type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys=None):
        if not boundaries:
            raise ValueError("boundaries must be a non-empty list")
        self._boundaries = sorted(boundaries)
        super().__init__(name, description, tag_keys)

    def observe(self, value: Union[int, float],
                tags: Optional[Dict[str, str]] = None) -> None:
        get_metrics_registry().observe(self._name, float(value),
                                       self._label_key(tags))
