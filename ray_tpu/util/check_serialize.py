"""Serializability inspection: find WHICH nested object breaks pickling.

Parity: reference ``python/ray/util/check_serialize.py``
(``inspect_serializability``): recursively descend into an
unserializable object's closure cells, attributes and members, pinpoint
the leaf objects that fail cloudpickle, and print a readable trace.
"""

from __future__ import annotations

import inspect
from typing import Any, NamedTuple, Optional, Set, Tuple

import cloudpickle


class FailureTuple(NamedTuple):
    """One offending object: where it lives and what holds it."""
    obj: Any
    name: str
    parent: Any

    def __repr__(self):
        return f"FailTuple({self.name} [obj={self.obj!r}, " \
               f"parent={self.parent!r}])"


def _serializable(obj: Any) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def _children(obj: Any):
    """(name, child) pairs worth descending into."""
    out = []
    if inspect.isfunction(obj):
        closure = getattr(obj, "__closure__", None) or ()
        names = obj.__code__.co_freevars
        for name, cell in zip(names, closure):
            try:
                out.append((name, cell.cell_contents))
            except ValueError:
                pass
        for name, value in (getattr(obj, "__globals__", {}) or {}).items():
            if name in obj.__code__.co_names and \
                    not inspect.ismodule(value):
                out.append((name, value))
    elif isinstance(obj, dict):
        out.extend((repr(k), v) for k, v in obj.items())
    elif isinstance(obj, (list, tuple, set)):
        out.extend((f"[{i}]", v) for i, v in enumerate(obj))
    else:
        for name, value in vars(type(obj)).items() \
                if not hasattr(obj, "__dict__") else \
                getattr(obj, "__dict__", {}).items():
            if not name.startswith("__"):
                out.append((name, value))
    return out


def inspect_serializability(
        base_obj: Any, name: Optional[str] = None, depth: int = 3,
        print_trace: bool = True,
) -> Tuple[bool, Set[FailureTuple]]:
    """Returns (serializable?, failure set of leaf offenders)."""
    name = name or getattr(base_obj, "__name__", repr(base_obj)[:40])
    failures: Set[FailureTuple] = set()
    ok = _inspect(base_obj, name, depth, None, failures)
    if print_trace and not ok:
        print(f"{'=' * 60}\n{name!r} is NOT serializable")
        for f in failures:
            print(f"  offender: {f.name} = {f.obj!r} "
                  f"(held by {f.parent!r})")
        print("=" * 60)
    return ok, failures


def _inspect(obj, name, depth, parent, failures) -> bool:
    if _serializable(obj):
        return True
    if depth <= 0:
        failures.add(FailureTuple(obj, name, parent))
        return False
    found_deeper = False
    for child_name, child in _children(obj):
        if not _serializable(child):
            found_deeper = True
            _inspect(child, f"{name}.{child_name}", depth - 1, obj,
                     failures)
    if not found_deeper:
        # This object itself is the leaf offender.
        failures.add(FailureTuple(obj, name, parent))
    return False
