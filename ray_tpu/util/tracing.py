"""Task-level tracing / timeline profiling.

Parity: reference OpenTelemetry tracing (``tracing_helper.py:157,314`` —
spans around submit/execute, context propagated by injecting a
``_ray_trace_ctx`` into every traced remote call; here the context rides
a ``TaskSpec.trace_ctx`` field) and the C++ ``ProfileEvent`` timeline
(``src/ray/core_worker/profiling.h:64``) batched back to the driver and
dumped as chrome://tracing JSON via ``ray.timeline()``
(``python/ray/state.py:843``).

Workers in other OS processes record spans locally and piggyback them on
task replies (``drain``/``ingest``), the in-process analogue of the
reference's ProfileEvent batching to GCS.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu._private.debug.lock_order import diag_lock

_lock = diag_lock("tracing._lock")
_events: List[dict] = []
# Fixed-capacity ring: a long traced run must not grow memory forever
# (task-event buffer semantics — loss is bounded, counted, and visible).
# Oldest events are dropped first; the cumulative counter is surfaced as
# an instant event on every drain and at /metrics.
_MAX_EVENTS = 100_000
_max_events = _MAX_EVENTS
_dropped = 0
_dropped_reported = 0       # drop count already emitted on a drain
_enabled = False
_tls = threading.local()


def enable(flag: bool = True):
    global _enabled
    _enabled = flag


def is_enabled() -> bool:
    return _enabled


def set_capacity(n: int) -> None:
    """Resize the ring (tests); existing overflow is dropped+counted."""
    global _max_events, _dropped
    with _lock:
        _max_events = max(1, int(n))
        overflow = len(_events) - _max_events
        if overflow > 0:
            del _events[:overflow]
            _dropped += overflow


def dropped_count() -> int:
    with _lock:
        return _dropped


def num_buffered() -> int:
    with _lock:
        return len(_events)


def _append_locked(event: dict) -> None:
    """Ring append (callers hold ``_lock``): over capacity, the OLDEST
    events go — the tail of a long run is the part worth keeping.  The
    trim drops a BATCH (1/16th of capacity), not one slot: a per-append
    single-slot `del _events[:1]` on a full ring would memmove the
    whole list under the lock on every span, serializing all tracing
    threads on the hot path."""
    global _dropped
    if len(_events) >= _max_events:
        overflow = len(_events) - _max_events + 1
        trim = max(overflow, _max_events // 16)
        del _events[:trim]
        _dropped += trim
    _events.append(event)


def current_context() -> Optional[Dict]:
    """The innermost active span's propagatable context, if any."""
    stack = getattr(_tls, "stack", None)
    return dict(stack[-1]) if stack else None


class span:
    """RAII profile span (ProfileEvent parity).

    ``parent`` is an explicit trace context dict (e.g. a TaskSpec's
    ``trace_ctx`` on the executor side); without one, the thread's
    innermost active span is the parent.  ``force`` records the span
    even when process-wide capture is off — executors use it so a
    traced task from a remote driver is captured in a worker process
    that never called :func:`enable`.
    """

    def __init__(self, name: str, category: str = "task",
                 parent: Optional[Dict] = None, force: bool = False,
                 **meta):
        self.name = name
        self.category = category
        self.meta = meta
        self.t0 = 0.0
        self._force = force
        self._parent = parent
        self._ctx: Optional[Dict] = None

    @property
    def active(self) -> bool:
        return _enabled or self._force

    def context(self) -> Optional[Dict]:
        """Propagatable context (inject into TaskSpec.trace_ctx)."""
        return dict(self._ctx) if self._ctx else None

    def __enter__(self):
        if not self.active:
            return self
        self.t0 = time.time()
        parent = self._parent or current_context()
        self._ctx = {
            "trace_id": (parent or {}).get("trace_id") or uuid.uuid4().hex,
            "span_id": uuid.uuid4().hex[:16],
            "parent_id": (parent or {}).get("span_id"),
        }
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self._ctx)
        return self

    def __exit__(self, *exc):
        if self._ctx is None:
            return
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self._ctx:
            stack.pop()
        args = dict(self.meta)
        args.update(self._ctx)
        with _lock:
            _append_locked({
                "name": self.name,
                "cat": self.category,
                "ph": "X",
                "ts": self.t0 * 1e6,
                "dur": (time.time() - self.t0) * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 2**31,
                "args": args,
            })


def record_instant(name: str, **meta):
    if not _enabled:
        return
    with _lock:
        _append_locked({"name": name, "ph": "i", "ts": time.time() * 1e6,
                        "pid": os.getpid(),
                        "tid": threading.get_ident() % 2**31,
                        "s": "g", "args": meta})


def _drop_marker_locked(consume: bool) -> Optional[dict]:
    """Instant event accounting for ring overflow (loss must be visible
    in the trace itself, not only in a counter).  Only ``drain`` — the
    transfer-of-ownership path — advances the reported watermark; a
    read-only dump must keep showing the marker on every call (a second
    ``timeline()`` of a truncated run must not look complete)."""
    global _dropped_reported
    if consume:
        if _dropped <= _dropped_reported:
            return None
        since = _dropped - _dropped_reported
        _dropped_reported = _dropped
    else:
        if _dropped <= 0:
            return None
        since = _dropped - _dropped_reported
    return {"name": "tracing.dropped", "ph": "i",
            "ts": time.time() * 1e6, "pid": os.getpid(),
            "tid": threading.get_ident() % 2**31, "s": "g",
            "args": {"dropped_total": _dropped,
                     "dropped_since_last": since}}


def chrome_tracing_dump() -> List[dict]:
    with _lock:
        out = list(_events)
        marker = _drop_marker_locked(consume=False)
    if marker is not None:
        out.append(marker)
    return out


def drain() -> List[dict]:
    """Atomically remove and return buffered events (worker side: ship
    them back on the task reply)."""
    with _lock:
        out = list(_events)
        _events.clear()
        marker = _drop_marker_locked(consume=True)
    if marker is not None:
        out.append(marker)
    return out


def ingest(events: Optional[List[dict]]):
    """Merge events recorded in another process into this timeline."""
    if not events:
        return
    with _lock:
        for ev in events:
            _append_locked(ev)


def clear():
    global _dropped, _dropped_reported
    with _lock:
        _events.clear()
        _dropped = 0
        _dropped_reported = 0


# /metrics surface for the ring's loss accounting — a scrape-time
# collector on a module-lifetime owner (the tracing buffer is process
# state, so its series never need churn-pruning).
class _TracingStatsOwner:
    pass


_stats_owner = _TracingStatsOwner()


def _register_stats_collector():
    try:
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
    except Exception:       # circular-import guard at bootstrap
        return

    def _collect(_owner):
        with _lock:
            dropped, buffered = _dropped, len(_events)
        record_internal("ray_tpu.tracing.dropped_events", dropped)
        record_internal("ray_tpu.tracing.buffered_events", buffered)

    get_metrics_registry().register_collector(_stats_owner, _collect)


_register_stats_collector()
