"""Task-level tracing / timeline profiling.

Parity: reference OpenTelemetry tracing (``tracing_helper.py`` — spans
around submit/execute with context propagation) and the C++ ``ProfileEvent``
timeline (``src/ray/core_worker/profiling.h:64``) dumped as chrome://tracing
JSON via ``ray.timeline()`` (``python/ray/state.py:843``).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

_lock = threading.Lock()
_events: List[dict] = []
_enabled = False


def enable(flag: bool = True):
    global _enabled
    _enabled = flag


def is_enabled() -> bool:
    return _enabled


class span:
    """RAII profile span (ProfileEvent parity)."""

    def __init__(self, name: str, category: str = "task", **meta):
        self.name = name
        self.category = category
        self.meta = meta
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        if not _enabled:
            return
        with _lock:
            _events.append({
                "name": self.name,
                "cat": self.category,
                "ph": "X",
                "ts": self.t0 * 1e6,
                "dur": (time.time() - self.t0) * 1e6,
                "pid": 0,
                "tid": threading.get_ident() % 2**31,
                "args": self.meta,
            })


def record_instant(name: str, **meta):
    if not _enabled:
        return
    with _lock:
        _events.append({"name": name, "ph": "i", "ts": time.time() * 1e6,
                        "pid": 0, "tid": threading.get_ident() % 2**31,
                        "s": "g", "args": meta})


def chrome_tracing_dump() -> List[dict]:
    with _lock:
        return list(_events)


def clear():
    with _lock:
        _events.clear()
