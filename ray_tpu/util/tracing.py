"""Task-level tracing / timeline profiling.

Parity: reference OpenTelemetry tracing (``tracing_helper.py:157,314`` —
spans around submit/execute, context propagated by injecting a
``_ray_trace_ctx`` into every traced remote call; here the context rides
a ``TaskSpec.trace_ctx`` field) and the C++ ``ProfileEvent`` timeline
(``src/ray/core_worker/profiling.h:64``) batched back to the driver and
dumped as chrome://tracing JSON via ``ray.timeline()``
(``python/ray/state.py:843``).

Workers in other OS processes record spans locally and piggyback them on
task replies (``drain``/``ingest``), the in-process analogue of the
reference's ProfileEvent batching to GCS.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Dict, List, Optional

_lock = threading.Lock()
_events: List[dict] = []
_enabled = False
_tls = threading.local()


def enable(flag: bool = True):
    global _enabled
    _enabled = flag


def is_enabled() -> bool:
    return _enabled


def current_context() -> Optional[Dict]:
    """The innermost active span's propagatable context, if any."""
    stack = getattr(_tls, "stack", None)
    return dict(stack[-1]) if stack else None


class span:
    """RAII profile span (ProfileEvent parity).

    ``parent`` is an explicit trace context dict (e.g. a TaskSpec's
    ``trace_ctx`` on the executor side); without one, the thread's
    innermost active span is the parent.  ``force`` records the span
    even when process-wide capture is off — executors use it so a
    traced task from a remote driver is captured in a worker process
    that never called :func:`enable`.
    """

    def __init__(self, name: str, category: str = "task",
                 parent: Optional[Dict] = None, force: bool = False,
                 **meta):
        self.name = name
        self.category = category
        self.meta = meta
        self.t0 = 0.0
        self._force = force
        self._parent = parent
        self._ctx: Optional[Dict] = None

    @property
    def active(self) -> bool:
        return _enabled or self._force

    def context(self) -> Optional[Dict]:
        """Propagatable context (inject into TaskSpec.trace_ctx)."""
        return dict(self._ctx) if self._ctx else None

    def __enter__(self):
        if not self.active:
            return self
        self.t0 = time.time()
        parent = self._parent or current_context()
        self._ctx = {
            "trace_id": (parent or {}).get("trace_id") or uuid.uuid4().hex,
            "span_id": uuid.uuid4().hex[:16],
            "parent_id": (parent or {}).get("span_id"),
        }
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self._ctx)
        return self

    def __exit__(self, *exc):
        if self._ctx is None:
            return
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self._ctx:
            stack.pop()
        args = dict(self.meta)
        args.update(self._ctx)
        with _lock:
            _events.append({
                "name": self.name,
                "cat": self.category,
                "ph": "X",
                "ts": self.t0 * 1e6,
                "dur": (time.time() - self.t0) * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 2**31,
                "args": args,
            })


def record_instant(name: str, **meta):
    if not _enabled:
        return
    with _lock:
        _events.append({"name": name, "ph": "i", "ts": time.time() * 1e6,
                        "pid": os.getpid(),
                        "tid": threading.get_ident() % 2**31,
                        "s": "g", "args": meta})


def chrome_tracing_dump() -> List[dict]:
    with _lock:
        return list(_events)


def drain() -> List[dict]:
    """Atomically remove and return buffered events (worker side: ship
    them back on the task reply)."""
    with _lock:
        out = list(_events)
        _events.clear()
    return out


def ingest(events: Optional[List[dict]]):
    """Merge events recorded in another process into this timeline."""
    if not events:
        return
    with _lock:
        _events.extend(events)


def clear():
    with _lock:
        _events.clear()
