"""ray_tpu.util — utilities on top of the public API.

Parity: reference ``python/ray/util/__init__.py`` (ActorPool, queue,
placement groups, scheduling strategies, collective, metrics, iter).
"""

from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup, get_current_placement_group, get_placement_group,
    placement_group, placement_group_table, remove_placement_group)
from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)

__all__ = [
    "placement_group", "remove_placement_group", "get_placement_group",
    "placement_group_table", "get_current_placement_group", "PlacementGroup",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "ActorPool",
]


def __getattr__(name):
    # Lazy submodule access for heavier utilities.
    if name == "ActorPool":
        from ray_tpu.util.actor_pool import ActorPool
        return ActorPool
    if name == "Queue":
        from ray_tpu.util.queue import Queue
        return Queue
    if name in ("collective", "metrics", "iter", "queue", "multiprocessing",
                "joblib"):
        import importlib
        try:
            return importlib.import_module(f"ray_tpu.util.{name}")
        except ImportError as e:
            raise AttributeError(
                f"module 'ray_tpu.util' has no attribute {name!r}") from e
    raise AttributeError(f"module 'ray_tpu.util' has no attribute {name!r}")
