"""Public placement-group API.

Parity: reference ``python/ray/util/placement_group.py`` —
``placement_group(bundles, strategy)``, ``PlacementGroup.ready()/wait()``,
``remove_placement_group``, ``get_placement_group`` (by name),
``placement_group_table``, ``get_current_placement_group``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private import worker_context
from ray_tpu._private.ids import PlacementGroupID
from ray_tpu.scheduler.resources import ResourceRequest


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID):
        self.id = pg_id

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        pg = self._gcs_pg()
        return [b.to_dict() for b in pg.bundles] if pg else []

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def _gcs_pg(self):
        w = worker_mod.global_worker()
        return w.cluster.gcs.placement_group_manager.get(self.id)

    def ready(self):
        """An ObjectRef sealed when the PG is placed (pg.ready() parity)."""
        from ray_tpu.remote_function import RemoteFunction
        pg = self

        def _ready_probe():
            return True

        rf = RemoteFunction(_ready_probe, dict(num_cpus=0, num_returns=1))
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy)
        return rf.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg,
            placement_group_bundle_index=0)).remote()

    def wait(self, timeout_seconds: Optional[float] = 30.0) -> bool:
        w = worker_mod.global_worker()
        return w.cluster.gcs.placement_group_manager.wait_ready(
            self.id, timeout_seconds)

    def __eq__(self, other):
        return isinstance(other, PlacementGroup) and self.id == other.id

    def __hash__(self):
        return hash(self.id)

    def __reduce__(self):
        return (PlacementGroup, (self.id,))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    w = worker_mod.global_worker()
    if not w.connected:
        worker_mod.init()
    if not bundles:
        raise ValueError("placement_group requires at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError(f"Invalid (empty) bundle: {b}")
        if any(v < 0 for v in b.values()):
            raise ValueError(f"Negative resources in bundle: {b}")
    from ray_tpu.gcs.placement_group_manager import GcsPlacementGroup
    pg_id = PlacementGroupID.from_random()
    gcs_pg = GcsPlacementGroup(
        pg_id, [ResourceRequest(b) for b in bundles], strategy,
        name=name, lifetime=lifetime or "")
    w.cluster.gcs.placement_group_manager.create_placement_group(gcs_pg)
    return PlacementGroup(pg_id)


def remove_placement_group(pg: PlacementGroup) -> None:
    w = worker_mod.global_worker()
    w.cluster.gcs.placement_group_manager.remove_placement_group(pg.id)


def get_placement_group(name: str) -> PlacementGroup:
    w = worker_mod.global_worker()
    gcs_pg = w.cluster.gcs.placement_group_manager.get_named(name)
    if gcs_pg is None:
        raise ValueError(f"Placement group {name!r} not found")
    return PlacementGroup(gcs_pg.pg_id)


def placement_group_table(pg: Optional[PlacementGroup] = None) -> dict:
    w = worker_mod.global_worker()
    table = w.cluster.gcs.placement_group_manager.table()
    if pg is not None:
        return table.get(pg.id.hex(), {})
    return table


def get_current_placement_group() -> Optional[PlacementGroup]:
    spec = worker_context.current_task_spec()
    if spec is None or spec.placement_group_id is None:
        return None
    return PlacementGroup(spec.placement_group_id)
