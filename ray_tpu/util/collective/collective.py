"""Collective group management + ops.

Parity: reference ``python/ray/util/collective/collective.py`` —
``init_collective_group`` (:115), ``create_collective_group`` (:143ish),
``allreduce`` (:253), ``broadcast`` (:368), ``allgather`` (:418),
``reducescatter`` (:467), ``send`` (:526), ``recv`` (:589).

Implementation: each group has a named rendezvous actor.  Every rank
contributes its tensor for a (seq, op) slot; when the slot is full the
rendezvous computes the result with one batched jax op (stack + reduce —
a single fused XLA kernel rather than a ring of P2P copies: on TPU the
reduction bandwidth is HBM-bound, and cross-actor tensors already travel
through host shared memory) and every rank fetches it.  Inside pjit/
shard_map, use lax.psum et al. directly — that plane needs no groups.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.util.collective.types import Backend, ReduceOp

_POLL_S = 0.002


@ray_tpu.remote
class _Rendezvous:
    """Per-group rendezvous: slot store for collectives + P2P mailboxes."""

    def __init__(self, world_size: int):
        self._world = world_size
        # (kind, seq) -> {rank: payload}
        self._slots: Dict[tuple, Dict[int, object]] = {}
        # (kind, seq) -> computed result (or list of per-rank results)
        self._results: Dict[tuple, object] = {}
        self._fetched: Dict[tuple, int] = {}
        # (src, dst) -> FIFO of tensors
        self._mail: Dict[tuple, List[object]] = {}

    def world_size(self) -> int:
        return self._world

    def contribute(self, key: tuple, rank: int, payload) -> None:
        self._slots.setdefault(key, {})[rank] = payload

    def slot_full(self, key: tuple) -> bool:
        return len(self._slots.get(key, {})) >= self._world

    def take_slot(self, key: tuple):
        """Return {rank: payload} once full, else None."""
        slot = self._slots.get(key)
        if slot is None or len(slot) < self._world:
            return None
        return slot

    def put_result(self, key: tuple, result) -> None:
        self._results[key] = result
        self._slots.pop(key, None)

    def fetch(self, key: tuple):
        """(ready, result); slot garbage-collected after world_size fetches."""
        if key not in self._results:
            return False, None
        res = self._results[key]
        n = self._fetched.get(key, 0) + 1
        if n >= self._world:
            self._results.pop(key, None)
            self._fetched.pop(key, None)
        else:
            self._fetched[key] = n
        return True, res

    # ---- point to point -------------------------------------------------
    def mail_put(self, src: int, dst: int, tensor) -> None:
        self._mail.setdefault((src, dst), []).append(tensor)

    def mail_get(self, src: int, dst: int):
        q = self._mail.get((src, dst))
        if not q:
            return False, None
        return True, q.pop(0)


class _GroupState:
    __slots__ = ("name", "world_size", "rank", "rendezvous", "seq", "lock")

    def __init__(self, name: str, world_size: int, rank: int, rendezvous):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.rendezvous = rendezvous
        self.seq = 0
        self.lock = threading.Lock()

    def next_seq(self) -> int:
        with self.lock:
            self.seq += 1
            return self.seq


# Group table keyed per executing actor/thread: in the in-process cluster
# actors are threads, so a flat per-process table would collide across
# ranks (reference has one table per OS process, collective.py:70).
_groups_lock = threading.Lock()
_groups: Dict[tuple, _GroupState] = {}
# Worker-local name aliases: lets library code (e.g. train's
# BackendExecutor) hand user functions a stable default name like
# "train" while the real group is scoped per run.
_aliases: Dict[tuple, str] = {}


def set_group_alias(alias: str, group_name: str) -> None:
    """In this worker, collective ops called with ``alias`` resolve to
    ``group_name``."""
    with _groups_lock:
        _aliases[_ctx_key(alias)] = group_name


def _resolve_name(group_name: str) -> str:
    with _groups_lock:
        return _aliases.get(_ctx_key(group_name), group_name)


def _ctx_key(group_name: str) -> tuple:
    from ray_tpu._private import worker_context
    ctx = worker_context.get_context()
    spec = ctx.task_spec
    actor_id = getattr(spec, "actor_id", None) if spec is not None else None
    owner = actor_id.hex() if actor_id else threading.get_ident()
    return (owner, group_name)


def _rendezvous_name(group_name: str) -> str:
    return f"collective_rendezvous:{group_name}"


def _get_or_create_rendezvous(group_name: str, world_size: int):
    name = _rendezvous_name(group_name)
    try:
        return ray_tpu.get_actor(name)
    except ValueError:
        pass
    try:
        return _Rendezvous.options(name=name, lifetime="detached").remote(
            world_size)
    except ValueError:
        # Lost the creation race; another rank made it.
        return ray_tpu.get_actor(name)


def init_collective_group(world_size: int, rank: int,
                          backend: str = "xla",
                          group_name: str = "default") -> None:
    """Declare this process/actor a member of a collective group
    (reference collective.py:115)."""
    Backend.normalize(backend)
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    key = _ctx_key(group_name)
    with _groups_lock:
        if key in _groups:
            raise RuntimeError(
                f"Group {group_name!r} already initialized in this worker")
    rdv = _get_or_create_rendezvous(group_name, world_size)
    state = _GroupState(group_name, world_size, rank, rdv)
    with _groups_lock:
        _groups[key] = state


def create_collective_group(actors, world_size: int, ranks: List[int],
                            backend: str = "xla",
                            group_name: str = "default") -> None:
    """Driver-side declaration for a list of actors
    (reference ``declare_collective_group``): calls
    ``init_collective_group`` inside each actor via an injected method,
    or expects the actor to expose ``init_collective_group``."""
    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must have the same length")
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor.init_collective_group.remote(
            world_size, rank, backend, group_name))
    ray_tpu.get(refs)


def destroy_collective_group(group_name: str = "default") -> None:
    actual = _resolve_name(group_name)
    with _groups_lock:
        state = _groups.pop(_ctx_key(actual), None)
        owner = _ctx_key("")[0]
        for k in [k for k, v in _aliases.items()
                  if v == actual and k[0] == owner]:
            _aliases.pop(k, None)
    if state is not None and state.rank == 0:
        try:
            ray_tpu.kill(state.rendezvous)
        except Exception:
            pass


def is_group_initialized(group_name: str = "default") -> bool:
    name = _resolve_name(group_name)
    with _groups_lock:
        return _ctx_key(name) in _groups


def get_rank(group_name: str = "default") -> int:
    state = _group(group_name)
    return state.rank


def get_collective_group_size(group_name: str = "default") -> int:
    state = _group(group_name)
    return state.world_size


def _group(group_name: str) -> _GroupState:
    group_name = _resolve_name(group_name)
    with _groups_lock:
        state = _groups.get(_ctx_key(group_name))
    if state is None:
        raise RuntimeError(
            f"Collective group {group_name!r} is not initialized in this "
            "worker; call init_collective_group first.")
    return state


# ---- reduction kernels (one fused XLA op over the stacked ranks) --------

def _reduce_stack(arrays: List[np.ndarray], op: ReduceOp):
    import jax.numpy as jnp
    stacked = jnp.stack([jnp.asarray(a) for a in arrays])
    if op == ReduceOp.SUM:
        out = jnp.sum(stacked, axis=0)
    elif op == ReduceOp.PRODUCT:
        out = jnp.prod(stacked, axis=0)
    elif op == ReduceOp.MIN:
        out = jnp.min(stacked, axis=0)
    elif op == ReduceOp.MAX:
        out = jnp.max(stacked, axis=0)
    elif op == ReduceOp.MEAN:
        out = jnp.mean(stacked, axis=0)
    else:
        raise ValueError(f"Unsupported ReduceOp {op}")
    return np.asarray(out)


def _run_collective(state: _GroupState, kind: str, payload, op=None):
    """Contribute + (rank-0 computes) + fetch."""
    key = (kind, state.next_seq(), str(op))
    rdv = state.rendezvous
    ray_tpu.get(rdv.contribute.remote(key, state.rank, payload))
    # Rank 0 computes once the slot fills; all ranks poll for the result.
    if state.rank == 0:
        while True:
            slot = ray_tpu.get(rdv.take_slot.remote(key))
            if slot is not None:
                result = _combine(kind, slot, op, state.world_size)
                ray_tpu.get(rdv.put_result.remote(key, result))
                break
            time.sleep(_POLL_S)
    while True:
        ready, res = ray_tpu.get(rdv.fetch.remote(key))
        if ready:
            return res
        time.sleep(_POLL_S)


def _combine(kind: str, slot: Dict[int, object], op, world: int):
    ordered = [slot[r] for r in range(world)]
    if kind == "allreduce":
        return _reduce_stack(ordered, op)
    if kind == "allgather":
        return [np.asarray(t) for t in ordered]
    if kind == "reducescatter":
        red = _reduce_stack(ordered, op)
        return np.array_split(red, world, axis=0)
    if kind == "broadcast":
        for t in ordered:
            if t is not None:
                return np.asarray(t)
        raise RuntimeError("broadcast: no source contribution")
    if kind == "barrier":
        return True
    raise ValueError(kind)


# ---- public ops ---------------------------------------------------------

def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    """All-reduce ``tensor`` across the group; returns the reduced array
    (reference collective.py:253 mutates in place; returning is the
    functional, jax-friendly form — callers rebind)."""
    state = _group(group_name)
    return _run_collective(state, "allreduce", np.asarray(tensor), op)


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    """Gather one tensor per rank, ordered by rank (reference :418)."""
    state = _group(group_name)
    return _run_collective(state, "allgather", np.asarray(tensor))


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
    """Reduce across ranks, then return this rank's shard along axis 0
    (reference :467)."""
    state = _group(group_name)
    shards = _run_collective(state, "reducescatter", np.asarray(tensor), op)
    return shards[state.rank]


def broadcast(tensor, src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    """Broadcast from ``src_rank`` to all ranks (reference :368)."""
    state = _group(group_name)
    payload = np.asarray(tensor) if state.rank == src_rank else None
    return _run_collective(state, "broadcast", payload)


def barrier(group_name: str = "default") -> None:
    """Block until every rank has entered the barrier."""
    state = _group(group_name)
    _run_collective(state, "barrier", True)


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """P2P send (reference :526) through the group mailbox."""
    state = _group(group_name)
    if dst_rank == state.rank:
        raise ValueError("cannot send to self")
    ray_tpu.get(state.rendezvous.mail_put.remote(
        state.rank, dst_rank, np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default",
         timeout: Optional[float] = None) -> np.ndarray:
    """P2P receive (reference :589); FIFO per (src, dst) channel."""
    state = _group(group_name)
    if src_rank == state.rank:
        raise ValueError("cannot recv from self")
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        ok, t = ray_tpu.get(state.rendezvous.mail_get.remote(
            src_rank, state.rank))
        if ok:
            return t
        if deadline is not None and time.monotonic() >= deadline:
            raise TimeoutError(f"recv from rank {src_rank} timed out")
        time.sleep(_POLL_S)
