"""Collective types (reference python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class ReduceOp(enum.Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    MEAN = 4  # extension: convenient for gradient averaging


class Backend:
    """Backend names (reference types.py Backend). NCCL/GLOO are mapped
    onto the XLA/object-store implementation so reference code runs
    unchanged."""

    XLA = "xla"
    NCCL = "nccl"
    GLOO = "gloo"

    @staticmethod
    def normalize(name: str) -> str:
        name = (name or "xla").lower()
        if name not in (Backend.XLA, Backend.NCCL, Backend.GLOO):
            raise ValueError(f"Unrecognized backend: {name!r}")
        return Backend.XLA
