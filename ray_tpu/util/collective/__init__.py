"""ray_tpu.util.collective — collective communication on actors/tasks.

Parity: reference ``python/ray/util/collective/collective.py`` (group
management + allreduce/allgather/reducescatter/broadcast/send/recv/
barrier).  Backend difference: the reference rendezvouses an NCCL unique
id through a named actor store and runs NCCL rings over NVLink/IB
(``collective_group/nccl_collective_group.py:127``); here the cross-actor
plane rendezvouses tensors through the object store and reduces them as
one batched XLA op, while the *intra-mesh* plane — SPMD code inside
``pjit``/``shard_map`` — uses native XLA collectives (psum/all_gather/
ppermute) over ICI and needs no group management at all (SURVEY.md §5.8).
"""

from ray_tpu.util.collective.collective import (  # noqa: F401
    allgather, allreduce, barrier, broadcast, create_collective_group,
    destroy_collective_group, get_collective_group_size, get_rank,
    init_collective_group, is_group_initialized, recv, reducescatter, send)
from ray_tpu.util.collective.types import ReduceOp  # noqa: F401

__all__ = [
    "init_collective_group", "create_collective_group",
    "destroy_collective_group", "is_group_initialized", "get_rank",
    "get_collective_group_size", "allreduce", "allgather", "reducescatter",
    "broadcast", "send", "recv", "barrier", "ReduceOp",
]
