"""Dask-on-ray_tpu: execute dask task graphs on the runtime.

Parity: reference ``python/ray/util/dask/scheduler.py`` —
``ray_dask_get`` walks a dask graph bottom-up, submits one runtime task
per graph task with upstream results passed as object refs (so the
object store, not the driver, holds intermediates), and gathers only
the requested keys; ``enable_dask_on_ray`` flips dask's default
scheduler.

Design difference: the reference leans on ``dask.core`` for graph
utilities.  A dask graph is plain data — a dict of
``key -> task | literal | key-alias`` where a task is a tuple whose
head is callable — so the walker here implements that spec directly
(``istask``/``toposort`` below) and works without dask installed;
``enable_dask_on_ray_tpu`` and the ``dask.compute`` integration
activate only when dask itself is importable.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Optional, Sequence

import ray_tpu

__all__ = ["ray_tpu_dask_get", "enable_dask_on_ray_tpu",
           "disable_dask_on_ray_tpu"]


def _ishashable(x: Any) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def istask(x: Any) -> bool:
    """A dask-spec task: a tuple whose first element is callable."""
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _dependencies(expr: Any, dsk: Dict) -> set:
    """Keys of ``dsk`` referenced (recursively) by ``expr``."""
    deps = set()
    stack = [expr]
    while stack:
        e = stack.pop()
        if istask(e):
            stack.extend(e[1:])
        elif isinstance(e, list):
            stack.extend(e)
        elif _ishashable(e) and e in dsk:
            # Includes non-task tuples: per the dask spec a hashable
            # non-task argument matching a graph key IS a reference.
            deps.add(e)
    return deps


def _execute_expr(expr: Any, results: Dict[Hashable, Any]) -> Any:
    """Evaluate a dask-spec expression given materialized upstreams.

    Runs INSIDE a runtime task: ``results`` maps the expression's
    dependency keys to their (already ray_tpu.get-resolved) values.
    """
    if istask(expr):
        fn = expr[0]
        args = [_execute_expr(a, results) for a in expr[1:]]
        return fn(*args)
    if isinstance(expr, list):
        return [_execute_expr(e, results) for e in expr]
    if _ishashable(expr) and expr in results:
        # Includes non-task tuple keys, e.g. ("x", 0) chunk keys.
        return results[expr]
    return expr


@ray_tpu.remote
def _dask_task(expr: Any, dep_keys: List[Hashable], *dep_values: Any):
    """One graph task: upstream values arrive as resolved task args
    (object refs at submit time — the scheduler's arg-locality and the
    object store do the data movement, reference scheduler.py
    _rayify_task)."""
    return _execute_expr(expr, dict(zip(dep_keys, dep_values)))


def _toposort(dsk: Dict, targets: Sequence[Hashable]) -> List[Hashable]:
    order: List[Hashable] = []
    seen: Dict[Hashable, int] = {}   # 0 = visiting, 1 = done
    stack = [(k, False) for k in targets]
    while stack:
        key, processed = stack.pop()
        if processed:
            seen[key] = 1
            order.append(key)
            continue
        state = seen.get(key)
        if state == 1:
            continue
        if state == 0:
            raise ValueError(f"cycle in dask graph at key {key!r}")
        seen[key] = 0
        stack.append((key, True))
        for dep in _dependencies(dsk[key], dsk):
            if seen.get(dep) != 1:
                stack.append((dep, False))
    return order


def ray_tpu_dask_get(dsk: Dict, keys, ray_remote_args: Optional[dict] = None,
                     **_kwargs):
    """Dask scheduler entry point (reference ``ray_dask_get``): submit
    the graph as runtime tasks and block on the requested ``keys``.

    ``keys`` may be a single key, a list of keys, or arbitrarily nested
    lists (dask passes nested key lists for collections)."""
    remote = _dask_task
    if ray_remote_args:
        remote = _dask_task.options(**ray_remote_args)
    refs: Dict[Hashable, Any] = {}

    flat: List[Hashable] = []

    def _flatten(ks):
        if isinstance(ks, list):
            for k in ks:
                _flatten(k)
        else:
            flat.append(ks)

    _flatten(keys)
    for key in _toposort(dsk, flat):
        expr = dsk[key]
        deps = sorted(_dependencies(expr, dsk), key=str)
        refs[key] = remote.remote(expr, deps, *[refs[d] for d in deps])

    # One batched get over every requested ref, then re-nest — not a
    # blocking round-trip per key.
    values = dict(zip(flat, ray_tpu.get([refs[k] for k in flat])))

    def _gather(ks):
        if isinstance(ks, list):
            return [_gather(k) for k in ks]
        return values[ks]

    return _gather(keys)


# Alias matching the reference's public name style.
ray_dask_get = ray_tpu_dask_get

_dask_config_ctx = None


def enable_dask_on_ray_tpu(shuffle: Optional[str] = "tasks"):
    """Make ``ray_tpu_dask_get`` dask's default scheduler (reference
    ``enable_dask_on_ray``).  Requires dask; returns the dask config
    context (usable as a context manager to scope the setting)."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray_tpu requires dask; the graph executor "
            "ray_tpu_dask_get(dsk, keys) works without it") from e
    return dask.config.set(scheduler=ray_tpu_dask_get, shuffle=shuffle)


def disable_dask_on_ray_tpu():
    import dask
    return dask.config.set(scheduler=None, shuffle=None)
