"""Distributed Queue backed by a named actor.

Parity: reference ``python/ray/util/queue.py`` (``Queue`` with
``put/get/put_nowait/get_nowait/put_async/get_async`` semantics,
``Empty``/``Full`` exceptions, batch variants, ``shutdown``).
"""

from __future__ import annotations

import queue as stdlib_queue
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = stdlib_queue.Queue(maxsize=maxsize)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()

    def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            self._q.put(item, block=timeout is not None and timeout > 0,
                        timeout=timeout)
            return True
        except stdlib_queue.Full:
            return False

    def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except stdlib_queue.Full:
            return False

    def put_nowait_batch(self, items: List[Any]) -> bool:
        if self._q.maxsize > 0 and self._q.qsize() + len(items) > self._q.maxsize:
            return False
        for item in items:
            self._q.put_nowait(item)
        return True

    def get(self, timeout: Optional[float] = None):
        try:
            if timeout is None:
                return True, self._q.get(block=True)
            return True, self._q.get(block=timeout > 0, timeout=timeout)
        except stdlib_queue.Empty:
            return False, None

    def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except stdlib_queue.Empty:
            return False, None

    def get_nowait_batch(self, num_items: int):
        if self._q.qsize() < num_items:
            return False, None
        return True, [self._q.get_nowait() for _ in range(num_items)]


class Queue:
    """A first-in-first-out queue usable from any task or actor.

    Backed by a (optionally named/detached) ``_QueueActor`` so producers
    and consumers anywhere in the cluster share one queue.
    """

    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        actor_options = actor_options or {}
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**actor_options).remote(maxsize)

    def __len__(self) -> int:
        return self.size()

    def size(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def qsize(self) -> int:
        return self.size()

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full
            return
        # Block by polling the actor (the actor's own blocking put would
        # wedge its single-threaded executor).
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put_nowait.remote(item)):
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full(f"Cannot add {len(items)} items to queue of size "
                       f"{self.maxsize}")

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty
            return item
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if ok:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty
            time.sleep(0.01)

    def get_nowait(self):
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        ok, items = ray_tpu.get(
            self.actor.get_nowait_batch.remote(num_items))
        if not ok:
            raise Empty(f"Cannot get {num_items} items from queue of size "
                        f"{self.size()}")
        return items

    def shutdown(self, force: bool = False) -> None:
        if self.actor is not None:
            ray_tpu.kill(self.actor)
        self.actor = None
