"""Remote pdb: debug a task running in a worker process over TCP.

Parity: reference ``python/ray/util/rpdb.py`` (``ray.util.pdb.set_trace``):
a task calls ``set_trace()``, a Pdb session binds a TCP port, and the
developer attaches with ``telnet``/``nc`` (or :func:`connect`).  The
bound address is printed to the worker's log — which the log pipeline
streams to the driver — so the user sees where to attach.
"""

from __future__ import annotations

import pdb
import socket
import sys


class _SocketIO:
    def __init__(self, conn: socket.socket):
        self._file_in = conn.makefile("r")
        self._file_out = conn.makefile("w")

    def readline(self):
        return self._file_in.readline()

    def write(self, data):
        self._file_out.write(data)

    def flush(self):
        self._file_out.flush()


class RemotePdb(pdb.Pdb):
    """Pdb bound to a TCP listener; one attach per breakpoint."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(1)
        self.address = self._listener.getsockname()
        print(f"RemotePdb waiting on {self.address[0]}:"
              f"{self.address[1]} — attach with "
              f"`nc {self.address[0]} {self.address[1]}`",
              file=sys.stderr, flush=True)
        conn, _ = self._listener.accept()
        self._conn = conn
        io = _SocketIO(conn)
        super().__init__(stdin=io, stdout=io)
        self.prompt = "(remote-pdb) "

    def do_continue(self, arg):
        out = super().do_continue(arg)
        self._close()
        return out

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        out = super().do_quit(arg)
        self._close()
        return out

    do_q = do_exit = do_quit

    def _close(self):
        for s in (self._conn, self._listener):
            try:
                s.close()
            except OSError:
                pass


def set_trace(host: str = "127.0.0.1", port: int = 0, frame=None):
    """Breakpoint inside a task/actor: blocks until a client attaches,
    then drives a normal pdb session over the socket."""
    debugger = RemotePdb(host=host, port=port)
    debugger.set_trace(frame or sys._getframe().f_back)


def connect(host: str, port: int):
    """Minimal interactive client (``nc`` equivalent) for tests and
    environments without netcat."""
    conn = socket.create_connection((host, port))
    return conn
