"""joblib backend: scikit-learn-style ``Parallel`` on framework tasks.

Parity: reference ``python/ray/util/joblib/`` — ``register_ray()``
installs a joblib parallel backend so ``with
joblib.parallel_backend("ray_tpu"): Parallel()(...)`` fans batches out
as tasks:

    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        results = joblib.Parallel()(joblib.delayed(f)(i) for i in data)
"""

from __future__ import annotations


def register_ray():
    """Register the 'ray_tpu' joblib parallel backend."""
    from joblib.parallel import ParallelBackendBase, register_parallel_backend

    import ray_tpu

    @ray_tpu.remote
    def _run_batch(batch):
        return batch()

    class _Future:
        def __init__(self, ref, callback):
            self._ref = ref
            self._callback = callback

        def get(self, timeout=None):
            value = ray_tpu.get(self._ref, timeout=timeout)
            if self._callback is not None:
                self._callback(value)
                self._callback = None
            return value

        def result(self, timeout=None):
            return self.get(timeout)

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        uses_threads = False
        supports_sharedmem = False

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            if n_jobs is None or n_jobs < 0:
                import os
                return os.cpu_count() or 1
            return n_jobs

        def apply_async(self, func, callback=None):
            ref = _run_batch.remote(func)
            future = _Future(ref, callback)
            if callback is not None:
                def fire(value, err):
                    if err is None and future._callback is not None:
                        cb, future._callback = future._callback, None
                        cb(value)

                from ray_tpu._private.worker import global_worker
                global_worker().core_worker.get_async(ref, fire)
            return future

        def configure(self, n_jobs=1, parallel=None, **_kwargs):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

    register_parallel_backend("ray_tpu", RayTpuBackend)
    register_parallel_backend("ray", RayTpuBackend)   # alias
