"""ParallelIterator — sharded iterators over actors.

Parity: reference ``python/ray/util/iter.py`` — ``from_items``,
``from_range``, ``from_iterators``, ``from_actors``;
``ParallelIterator.for_each/filter/batch/flatten/combine/
batch_across_shards/gather_sync/gather_async/take/show/union/
num_shards/shards``; ``LocalIterator`` with the same transforms.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Callable, Iterable, Iterator, List, TypeVar

import ray_tpu

T = TypeVar("T")
U = TypeVar("U")


@ray_tpu.remote
class ParallelIteratorWorker:
    """Actor hosting one shard (reference iter.py ParallelIteratorWorker)."""

    def __init__(self, item_generator, repeat: bool = False):
        self._gen = item_generator
        self._repeat = repeat
        self._it = None
        self._transforms: List[Callable[[Iterator], Iterator]] = []

    def add_transform(self, fn) -> None:
        self._transforms.append(fn)

    def _base_iterator(self) -> Iterator:
        while True:
            if callable(self._gen):
                it = self._gen()
            else:
                it = iter(self._gen)
            for item in it:
                yield item
            if not self._repeat:
                return

    def start(self) -> None:
        it = self._base_iterator()
        for t in self._transforms:
            it = t(it)
        self._it = it

    def par_iter_next(self):
        if self._it is None:
            self.start()
        return next(self._it)

    def par_iter_slice(self, step: int, start: int):
        """Next item of an interleaved slice (for multiple consumers)."""
        if self._it is None:
            self.start()
        return next(itertools.islice(self._it, start, start + 1))


class ParallelIterator:
    """A parallel iterator over ``num_shards`` actor-hosted shards."""

    def __init__(self, actors: List[Any], parent_iterators=None,
                 name: str = "ParallelIterator"):
        self.actors = actors
        self.name = name

    def __repr__(self):
        return f"{self.name}[{len(self.actors)} shards]"

    def num_shards(self) -> int:
        return len(self.actors)

    def shards(self) -> List["LocalIterator"]:
        return [_shard_iterator(a) for a in self.actors]

    # ---- transforms (applied remotely, lazily per shard) ----------------
    def _with_transform(self, make_transform, name_suffix: str):
        ray_tpu.get([a.add_transform.remote(make_transform)
                     for a in self.actors])
        self.name += name_suffix
        return self

    def for_each(self, fn: Callable[[T], U]) -> "ParallelIterator":
        return self._with_transform(
            lambda it, fn=fn: map(fn, it), f".for_each({fn})")

    def filter(self, fn: Callable[[T], bool]) -> "ParallelIterator":
        return self._with_transform(
            lambda it, fn=fn: (x for x in it if fn(x)), f".filter({fn})")

    def batch(self, n: int) -> "ParallelIterator":
        def batcher(it, n=n):
            batch = []
            for x in it:
                batch.append(x)
                if len(batch) >= n:
                    yield batch
                    batch = []
            if batch:
                yield batch
        return self._with_transform(batcher, f".batch({n})")

    def flatten(self) -> "ParallelIterator":
        return self._with_transform(
            lambda it: (x for sub in it for x in sub), ".flatten()")

    def combine(self, fn: Callable[[T], Iterable[U]]) -> "ParallelIterator":
        return self.for_each(fn).flatten()

    # ---- gathering ------------------------------------------------------
    def gather_sync(self) -> "LocalIterator":
        """Round-robin over shards, one item per shard per cycle."""
        def gen():
            alive = list(self.actors)
            while alive:
                nxt = []
                for a in alive:
                    try:
                        yield ray_tpu.get(a.par_iter_next.remote())
                        nxt.append(a)
                    except StopIteration:
                        pass
                alive = nxt
        return LocalIterator(gen, name=self.name + ".gather_sync()")

    def gather_async(self, num_async: int = 1) -> "LocalIterator":
        """Yield items as shards produce them (reference gather_async)."""
        def gen():
            inflight = {}
            for a in self.actors:
                for _ in range(num_async):
                    inflight[a.par_iter_next.remote()] = a
            while inflight:
                ready, _ = ray_tpu.wait(list(inflight), num_returns=1)
                ref = ready[0]
                actor = inflight.pop(ref)
                try:
                    yield ray_tpu.get(ref)
                except StopIteration:
                    continue
                inflight[actor.par_iter_next.remote()] = actor
        return LocalIterator(gen, name=self.name + ".gather_async()")

    def batch_across_shards(self) -> "LocalIterator":
        """One list per cycle containing one item from every shard."""
        def gen():
            while True:
                refs = [a.par_iter_next.remote() for a in self.actors]
                try:
                    yield ray_tpu.get(refs)
                except StopIteration:
                    return
        return LocalIterator(gen,
                             name=self.name + ".batch_across_shards()")

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        return ParallelIterator(self.actors + other.actors,
                                name=f"{self.name}.union({other.name})")

    # ---- consumption helpers -------------------------------------------
    def take(self, n: int) -> List[T]:
        return self.gather_sync().take(n)

    def show(self, n: int = 20) -> None:
        for item in self.take(n):
            print(item)

    def __iter__(self):
        return iter(self.gather_sync())


def _shard_iterator(actor) -> "LocalIterator":
    def gen():
        while True:
            try:
                yield ray_tpu.get(actor.par_iter_next.remote())
            except StopIteration:
                return
    return LocalIterator(gen, name="shard")


class LocalIterator:
    """A local, lazily-evaluated iterator with the same transform API."""

    def __init__(self, base_gen: Callable[[], Iterator],
                 name: str = "LocalIterator"):
        self._base_gen = base_gen
        self.name = name

    def __iter__(self):
        return self._base_gen()

    def __next__(self):
        if not hasattr(self, "_it"):
            self._it = self._base_gen()
        return next(self._it)

    def for_each(self, fn) -> "LocalIterator":
        base = self._base_gen
        return LocalIterator(lambda: map(fn, base()),
                             name=self.name + f".for_each({fn})")

    def filter(self, fn) -> "LocalIterator":
        base = self._base_gen
        return LocalIterator(lambda: (x for x in base() if fn(x)),
                             name=self.name + f".filter({fn})")

    def batch(self, n: int) -> "LocalIterator":
        base = self._base_gen

        def gen():
            batch = []
            for x in base():
                batch.append(x)
                if len(batch) >= n:
                    yield batch
                    batch = []
            if batch:
                yield batch
        return LocalIterator(gen, name=self.name + f".batch({n})")

    def flatten(self) -> "LocalIterator":
        base = self._base_gen
        return LocalIterator(lambda: (x for sub in base() for x in sub),
                             name=self.name + ".flatten()")

    def combine(self, fn) -> "LocalIterator":
        return self.for_each(fn).flatten()

    def zip_with_source_actor(self):
        raise NotImplementedError("zip_with_source_actor: driver-side only")

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(iter(self), n))

    def show(self, n: int = 20) -> None:
        for item in self.take(n):
            print(item)

    def union(self, other: "LocalIterator") -> "LocalIterator":
        a, b = self._base_gen, other._base_gen

        def gen():
            its = [a(), b()]
            q = collections.deque(its)
            while q:
                it = q.popleft()
                try:
                    yield next(it)
                    q.append(it)
                except StopIteration:
                    pass
        return LocalIterator(gen, name=f"{self.name}.union({other.name})")


# ---- constructors -------------------------------------------------------

def from_iterators(generators: List[Any], repeat: bool = False,
                   name=None) -> ParallelIterator:
    actors = [ParallelIteratorWorker.remote(g, repeat) for g in generators]
    return ParallelIterator(
        actors, name=name or f"from_iterators[shards={len(generators)}]")


def from_items(items: List[T], num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    shards: List[List[T]] = [[] for _ in range(num_shards)]
    for i, item in enumerate(items):
        shards[i % num_shards].append(item)
    return from_iterators(shards, repeat,
                          name=f"from_items[{len(items)} items, "
                               f"{num_shards} shards]")


def from_range(n: int, num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    gens = []
    for i in range(num_shards):
        start = i * (n // num_shards)
        end = (i + 1) * (n // num_shards) if i < num_shards - 1 else n
        gens.append(range(start, end))
    return from_iterators(gens, repeat,
                          name=f"from_range[{n}, {num_shards} shards]")


def from_actors(actors: List[Any], name=None) -> ParallelIterator:
    """Wrap existing ParallelIteratorWorker-compatible actors."""
    return ParallelIterator(actors, name=name or "from_actors")
