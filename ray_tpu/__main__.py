"""``python -m ray_tpu <command>`` — the CLI entry
(reference: the installed ``ray`` console script)."""

from ray_tpu.scripts.cli import main

raise SystemExit(main())
