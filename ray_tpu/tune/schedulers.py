"""Trial schedulers: early stopping / resource-adaptive policies.

Parity: reference ``python/ray/tune/schedulers/`` — ``FIFOScheduler``
(``trial_scheduler.py``), ``AsyncHyperBandScheduler``/ASHA
(``async_hyperband.py``: brackets of halving rungs, cutoff at the top
1/reduction_factor quantile per rung), ``MedianStoppingRule``
(``median_stopping_rule.py``), ``PopulationBasedTraining`` (``pbt.py``:
exploit bottom quantile from top quantile + explore/perturb config).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.sample import Domain
from ray_tpu.tune.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"
    PAUSE = "PAUSE"

    def on_trial_add(self, trial: Trial):
        pass

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        return TrialScheduler.CONTINUE

    def on_trial_complete(self, trial: Trial, result: Optional[Dict]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class _Rung:
    def __init__(self, milestone: int):
        self.milestone = milestone
        self.recorded: Dict[str, float] = {}  # trial_id -> metric


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference async_hyperband.py). One bracket by default:
    rungs at grace_period * reduction_factor^k; a trial reaching a rung
    continues only if in the top 1/reduction_factor of that rung."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, max_t: int = 100,
                 reduction_factor: float = 3):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._rf = reduction_factor
        self._max_t = max_t
        self.rungs: List[_Rung] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(_Rung(int(t)))
            t *= reduction_factor
        self.stopped = 0

    def _value(self, result: Dict) -> Optional[float]:
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        t = result.get(self._time_attr, 0)
        if t >= self._max_t:
            return TrialScheduler.STOP
        v = self._value(result)
        if v is None:
            return TrialScheduler.CONTINUE
        action = TrialScheduler.CONTINUE
        for rung in self.rungs:
            if t < rung.milestone or trial.trial_id in rung.recorded:
                continue
            rung.recorded[trial.trial_id] = v
            vals = sorted(rung.recorded.values(), reverse=True)
            k = max(1, int(len(vals) / self._rf))
            cutoff = vals[k - 1]
            if v < cutoff:
                action = TrialScheduler.STOP
        if action == TrialScheduler.STOP:
            self.stopped += 1
        return action


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference
    median_stopping_rule.py)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._histories: Dict[str, List[float]] = {}

    def _value(self, result: Dict) -> Optional[float]:
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        v = self._value(result)
        t = result.get(self._time_attr, 0)
        if v is None:
            return TrialScheduler.CONTINUE
        self._histories.setdefault(trial.trial_id, []).append(v)
        if t < self._grace or len(self._histories) < self._min_samples:
            return TrialScheduler.CONTINUE
        means = [sum(h) / len(h) for tid, h in self._histories.items()
                 if tid != trial.trial_id and h]
        if not means:
            return TrialScheduler.CONTINUE
        means.sort()
        median = means[len(means) // 2]
        best = max(self._histories[trial.trial_id])
        return TrialScheduler.STOP if best < median \
            else TrialScheduler.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference pbt.py): every ``perturbation_interval`` steps, a
    bottom-quantile trial exploits (copies config+checkpoint of) a
    top-quantile trial and explores (perturbs) its hyperparameters."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._trials: List[Trial] = []
        self.num_perturbations = 0

    def on_trial_add(self, trial: Trial):
        self._trials.append(trial)

    def _score(self, trial: Trial) -> Optional[float]:
        v = trial.metric(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for k, spec in self._mutations.items():
            if self._rng.random() < self._resample_prob:
                out[k] = spec.sample(self._rng) if isinstance(spec, Domain) \
                    else self._rng.choice(spec)
            elif isinstance(out.get(k), (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[k] = type(out[k])(out[k] * factor)
        return out

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        t = result.get(self._time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self._interval:
            return TrialScheduler.CONTINUE
        self._last_perturb[trial.trial_id] = t
        scored = [(self._score(x), x) for x in self._trials
                  if self._score(x) is not None]
        if len(scored) < 2:
            return TrialScheduler.CONTINUE
        scored.sort(key=lambda p: p[0])
        n = max(1, int(len(scored) * self._quantile))
        bottom = [x for _, x in scored[:n]]
        top = [x for _, x in scored[-n:]]
        if trial in bottom and trial not in top:
            model = self._rng.choice(top)
            trial.config = self._explore(model.config)
            trial.checkpoint = model.checkpoint
            self.num_perturbations += 1
            # Restart with the exploited config+checkpoint.
            return TrialScheduler.PAUSE
        return TrialScheduler.CONTINUE
