"""Trial schedulers: early stopping / resource-adaptive policies.

Parity: reference ``python/ray/tune/schedulers/`` — ``FIFOScheduler``
(``trial_scheduler.py``), ``AsyncHyperBandScheduler``/ASHA
(``async_hyperband.py``: brackets of halving rungs, cutoff at the top
1/reduction_factor quantile per rung), ``MedianStoppingRule``
(``median_stopping_rule.py``), ``PopulationBasedTraining`` (``pbt.py``:
exploit bottom quantile from top quantile + explore/perturb config).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.sample import Domain
from ray_tpu.tune.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"
    PAUSE = "PAUSE"

    def on_trial_add(self, trial: Trial):
        pass

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        return TrialScheduler.CONTINUE

    def on_trial_complete(self, trial: Trial, result: Optional[Dict]):
        pass

    def choose_trial_to_run(self, trials: List[Trial]) -> Optional[Trial]:
        """Pick the next trial the runner should (re)start (reference
        trial_scheduler.py choose_trial_to_run).  Synchronous schedulers
        override this to hold PAUSED trials until their cohort decides."""
        for t in trials:
            if t.status in (Trial.PENDING, Trial.PAUSED):
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass


class _Rung:
    def __init__(self, milestone: int):
        self.milestone = milestone
        self.recorded: Dict[str, float] = {}  # trial_id -> metric


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference async_hyperband.py). One bracket by default:
    rungs at grace_period * reduction_factor^k; a trial reaching a rung
    continues only if in the top 1/reduction_factor of that rung."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, max_t: int = 100,
                 reduction_factor: float = 3):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._rf = reduction_factor
        self._max_t = max_t
        self.rungs: List[_Rung] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(_Rung(int(t)))
            t *= reduction_factor
        self.stopped = 0

    def _value(self, result: Dict) -> Optional[float]:
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        t = result.get(self._time_attr, 0)
        if t >= self._max_t:
            return TrialScheduler.STOP
        v = self._value(result)
        if v is None:
            return TrialScheduler.CONTINUE
        action = TrialScheduler.CONTINUE
        for rung in self.rungs:
            if t < rung.milestone or trial.trial_id in rung.recorded:
                continue
            rung.recorded[trial.trial_id] = v
            vals = sorted(rung.recorded.values(), reverse=True)
            k = max(1, int(len(vals) / self._rf))
            cutoff = vals[k - 1]
            if v < cutoff:
                action = TrialScheduler.STOP
        if action == TrialScheduler.STOP:
            self.stopped += 1
        return action


class _Bracket:
    """One synchronous successive-halving bracket."""

    def __init__(self, capacity: int, r0: int, eta: float, max_t: int):
        self.capacity = capacity
        self.milestone = r0
        self.eta = eta
        self.max_t = max_t
        self.added = 0                         # trials EVER assigned
        self.halved = False
        self.closed = False                    # no more trials coming
        self.live: List[Trial] = []
        self.recorded: Dict[str, float] = {}   # trial_id -> metric
        self.resumable: set = set()            # trial_ids cleared to run

    def full(self) -> bool:
        # Count trials ever added, not the live list — halving shrinks
        # live, and a bracket must not keep absorbing new trials (which
        # would join at an already-advanced milestone and skip the
        # early rungs the incumbents were filtered at).
        return self.added >= self.capacity or self.halved

    def quorum(self) -> bool:
        # The first halving waits for the bracket to actually FILL (or
        # for the source to run dry — ``closed``): with a lazy variant
        # source, halving over just the trials that happen to have
        # arrived would shrink every cohort to the concurrency level
        # (and to 1 at max_concurrent_trials=1, a silent no-op).
        if not self.live or len(self.recorded) < len(self.live):
            return False
        return self.halved or self.closed or self.added >= self.capacity

    def halve(self) -> set:
        """Keep the top 1/eta, terminate the rest.  Returns the
        surviving trial_ids; losers that are PAUSED are terminated here
        (their actors are already stopped), a loser still RUNNING gets
        STOP from on_trial_result."""
        self.halved = True
        ranked = sorted(self.live,
                        key=lambda t: self.recorded[t.trial_id],
                        reverse=True)
        k = max(1, int(math.ceil(len(ranked) / self.eta)))
        survivors, losers = ranked[:k], ranked[k:]
        for t in losers:
            if t.status == Trial.PAUSED:
                t.status = Trial.TERMINATED
        self.live = survivors
        self.milestone = min(int(self.milestone * self.eta), self.max_t)
        self.recorded = {}
        ids = {t.trial_id for t in survivors}
        self.resumable |= ids
        return ids


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference ``hyperband.py``): trials fill
    successive-halving brackets of geometrically varying width/budget;
    each bracket waits (PAUSE) for all members to reach the current
    milestone, keeps the top 1/eta, and multiplies the milestone by eta.
    Unlike ASHA the halving decision sees the whole cohort, so stragglers
    are held at the rung instead of racing ahead.

    Pausing stops the trial's actor; survivors resume from
    ``trial.checkpoint``.  ``Trainable`` subclasses checkpoint every
    step automatically, so resumption is free.  FUNCTION trainables must
    call ``tune.save_checkpoint(...)`` (and restore via
    ``tune.load_checkpoint()``) to resume from the rung — otherwise a
    paused survivor re-runs from iteration 1 (correct result, duplicated
    compute, and regressed ``training_iteration`` values re-reported to
    the searcher)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._eta = reduction_factor
        self._max_t = max_t
        # Bracket ladder: s = s_max..0, bracket s starts
        # n_s = ceil((s_max+1)/(s+1) * eta^s) trials at budget
        # r_s = max_t * eta^-s (the HyperBand paper's outer loop).
        s_max = int(math.log(max_t, reduction_factor))
        self._specs = []
        for s in range(s_max, -1, -1):
            n = int(math.ceil((s_max + 1) / (s + 1)
                              * reduction_factor ** s))
            r = max(1, int(max_t * reduction_factor ** (-s)))
            self._specs.append((n, r))
        self._brackets: List[_Bracket] = []
        self._spec_idx = 0
        self._by_trial: Dict[str, _Bracket] = {}
        self.stopped = 0

    def _value(self, result: Dict) -> Optional[float]:
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_add(self, trial: Trial):
        if not self._brackets or self._brackets[-1].full():
            n, r = self._specs[self._spec_idx % len(self._specs)]
            self._spec_idx += 1
            self._brackets.append(
                _Bracket(n, r, self._eta, self._max_t))
        b = self._brackets[-1]
        b.added += 1
        b.live.append(trial)
        self._by_trial[trial.trial_id] = b

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        b = self._by_trial.get(trial.trial_id)
        t = result.get(self._time_attr, 0)
        if b is None or trial not in b.live:
            return TrialScheduler.STOP
        if t >= self._max_t:
            b.live.remove(trial)
            b.recorded.pop(trial.trial_id, None)
            if b.quorum():
                b.halve()
            return TrialScheduler.STOP
        v = self._value(result)
        if v is None or t < b.milestone:
            return TrialScheduler.CONTINUE
        b.recorded[trial.trial_id] = v
        if not b.quorum():
            return TrialScheduler.PAUSE     # wait for the cohort
        survivors = b.halve()
        if trial.trial_id in survivors:
            b.resumable.discard(trial.trial_id)   # it is already running
            return TrialScheduler.CONTINUE
        self.stopped += 1
        return TrialScheduler.STOP

    def on_trial_complete(self, trial: Trial, result: Optional[Dict]):
        b = self._by_trial.pop(trial.trial_id, None)
        if b is None or trial not in b.live:
            return
        b.live.remove(trial)
        b.recorded.pop(trial.trial_id, None)
        b.resumable.discard(trial.trial_id)
        if b.quorum():
            b.halve()

    def choose_trial_to_run(self, trials: List[Trial]) -> Optional[Trial]:
        for t in trials:
            if t.status == Trial.PENDING:
                return t
        for t in trials:
            if t.status == Trial.PAUSED:
                b = self._by_trial.get(t.trial_id)
                if b is not None and t.trial_id in b.resumable:
                    b.resumable.discard(t.trial_id)
                    return t
        return None

    def no_more_trials(self):
        """The variant source is exhausted (runner callback): brackets
        that were waiting to fill will never fill — close them and
        halve any whose cohort has fully recorded, so paused trials
        resume instead of waiting forever."""
        for b in self._brackets:
            b.closed = True
            if b.quorum():
                b.halve()


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference
    median_stopping_rule.py)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._histories: Dict[str, List[float]] = {}

    def _value(self, result: Dict) -> Optional[float]:
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        v = self._value(result)
        t = result.get(self._time_attr, 0)
        if v is None:
            return TrialScheduler.CONTINUE
        self._histories.setdefault(trial.trial_id, []).append(v)
        if t < self._grace or len(self._histories) < self._min_samples:
            return TrialScheduler.CONTINUE
        means = [sum(h) / len(h) for tid, h in self._histories.items()
                 if tid != trial.trial_id and h]
        if not means:
            return TrialScheduler.CONTINUE
        means.sort()
        median = means[len(means) // 2]
        best = max(self._histories[trial.trial_id])
        return TrialScheduler.STOP if best < median \
            else TrialScheduler.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference pbt.py): every ``perturbation_interval`` steps, a
    bottom-quantile trial exploits (copies config+checkpoint of) a
    top-quantile trial and explores (perturbs) its hyperparameters."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._trials: List[Trial] = []
        self.num_perturbations = 0

    def on_trial_add(self, trial: Trial):
        self._trials.append(trial)

    def _score(self, trial: Trial) -> Optional[float]:
        v = trial.metric(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for k, spec in self._mutations.items():
            if self._rng.random() < self._resample_prob:
                out[k] = spec.sample(self._rng) if isinstance(spec, Domain) \
                    else self._rng.choice(spec)
            elif isinstance(out.get(k), (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[k] = type(out[k])(out[k] * factor)
        return out

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        t = result.get(self._time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self._interval:
            return TrialScheduler.CONTINUE
        self._last_perturb[trial.trial_id] = t
        scored = [(self._score(x), x) for x in self._trials
                  if self._score(x) is not None]
        if len(scored) < 2:
            return TrialScheduler.CONTINUE
        scored.sort(key=lambda p: p[0])
        n = max(1, int(len(scored) * self._quantile))
        bottom = [x for _, x in scored[:n]]
        top = [x for _, x in scored[-n:]]
        if trial in bottom and trial not in top:
            model = self._rng.choice(top)
            trial.config = self._explore(model.config)
            trial.checkpoint = model.checkpoint
            self.num_perturbations += 1
            # Restart with the exploited config+checkpoint.
            return TrialScheduler.PAUSE
        return TrialScheduler.CONTINUE
